"""Process-level churn: daemons and the scheduler die mid-transfer.

VERDICT next #6 (carried from rounds 1-3). Real OS processes, paced origin
(bench role) so tasks span many seconds and kills land mid-flight:

- a parent daemon is SIGKILLed mid-transfer: its children re-home (seed /
  other peers) and finish byte-identical;
- the scheduler is killed and restarted mid-task: in-flight downloads
  survive on their existing sync streams and finish;
- a streaming consumer (daemon proxy) keeps its ordered byte stream
  intact while a parent dies under it (reference
  peertask_stream_resume_test.go).
"""

import hashlib
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

# three multi-process kill scenarios against paced origins: minutes of
# wall time by design — tier-1 excludes it (ROADMAP -m 'not slow')
pytestmark = pytest.mark.slow

from test_launchers import free_port, wait_line

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


def start_origin(procs, path: str, mbps: float) -> int:
    p = subprocess.Popen(
        [PY, os.path.join(REPO, "bench.py"), "--role", "origin",
         path, str(mbps)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env={**os.environ, "PYTHONPATH": REPO}, cwd=REPO)
    procs.append(p)
    return json.loads(p.stdout.readline())["port"]


def start_daemon(procs, tmp_path, name: str, extra: dict) -> subprocess.Popen:
    cfg = {"workdir": str(tmp_path / name), "host_ip": "127.0.0.1",
           "hostname": name, "storage": {"gc_interval_s": 3600}, **extra}
    cfg_path = tmp_path / f"{name}.json"
    cfg_path.write_text(json.dumps(cfg))
    p = subprocess.Popen(
        [PY, "-m", "dragonfly2_tpu.tools.daemon", "--config", str(cfg_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "PYTHONPATH": REPO, "PYTHONUNBUFFERED": "1",
             "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    procs.append(p)
    wait_line(p, "daemon up:")
    return p


def start_scheduler(procs, seed_rpc: int, seed_dl: int,
                    port: int) -> subprocess.Popen:
    cfg = json.dumps({"port": port, "advertise_ip": "127.0.0.1",
                      "seed_peers": [{"ip": "127.0.0.1",
                                      "rpc_port": seed_rpc,
                                      "download_port": seed_dl}]})
    import tempfile
    f = tempfile.NamedTemporaryFile("w", suffix=".json", delete=False)
    f.write(cfg)
    f.close()
    p = subprocess.Popen(
        [PY, "-m", "dragonfly2_tpu.tools.scheduler", "--config", f.name],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "PYTHONPATH": REPO, "PYTHONUNBUFFERED": "1",
             "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    procs.append(p)
    wait_line(p, "scheduler up:")
    return p


def dfget(sock: str, url: str, out: str) -> subprocess.Popen:
    return subprocess.Popen(
        [PY, "-m", "dragonfly2_tpu.tools.dfget", url, "-O", out,
         "--daemon-sock", sock, "--quiet"],
        env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"},
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True)


def teardown(procs):
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=10)
        except (subprocess.TimeoutExpired, OSError):
            p.kill()


def test_parent_daemon_killed_mid_transfer(tmp_path):
    """Child A gets ahead and becomes B's parent; A is SIGKILLed while B is
    mid-download. B must re-home (seed/others) and finish byte-identical."""
    blob = os.urandom(48 << 20)          # 12 pieces; ~12s at 4 MB/s
    data = tmp_path / "blob.bin"
    data.write_bytes(blob)
    procs = []
    try:
        origin_port = start_origin(procs, str(data), 4.0)
        url = f"http://127.0.0.1:{origin_port}/blob.bin"
        seed_rpc, seed_up = free_port(), free_port()
        start_daemon(procs, tmp_path, "seed",
                     {"is_seed": True, "rpc_port": seed_rpc,
                      "upload": {"port": seed_up}})
        sched_port = free_port()
        start_scheduler(procs, seed_rpc, seed_up, sched_port)
        sched_addr = f"127.0.0.1:{sched_port}"

        sock_a = str(tmp_path / "a.sock")
        sock_b = str(tmp_path / "b.sock")
        pa = start_daemon(procs, tmp_path, "peer-a",
                          {"unix_sock": sock_a,
                           "scheduler": {"addresses": [sched_addr]}})
        start_daemon(procs, tmp_path, "peer-b",
                     {"unix_sock": sock_b,
                      "scheduler": {"addresses": [sched_addr]}})

        out_a = str(tmp_path / "a.out")
        out_b = str(tmp_path / "b.out")
        pull_a = dfget(sock_a, url, out_a)
        time.sleep(4)                    # A accumulates pieces first
        pull_b = dfget(sock_b, url, out_b)
        time.sleep(4)                    # B mid-download, A is a parent
        pa.kill()                        # SIGKILL: no goodbyes
        pull_a.kill()
        rc = pull_b.wait(timeout=120)
        assert rc == 0, pull_b.stderr.read()[-1500:]
        got = hashlib.sha256(open(out_b, "rb").read()).hexdigest()
        assert got == hashlib.sha256(blob).hexdigest()
    finally:
        teardown(procs)


def test_scheduler_restart_mid_task(tmp_path):
    """The scheduler dies and comes back (same port) while a download is in
    flight: existing sync streams keep feeding the child — losing the
    control plane must not kill data-plane transfers."""
    blob = os.urandom(48 << 20)
    data = tmp_path / "blob.bin"
    data.write_bytes(blob)
    procs = []
    try:
        origin_port = start_origin(procs, str(data), 4.0)
        url = f"http://127.0.0.1:{origin_port}/blob.bin"
        seed_rpc, seed_up = free_port(), free_port()
        start_daemon(procs, tmp_path, "seed",
                     {"is_seed": True, "rpc_port": seed_rpc,
                      "upload": {"port": seed_up}})
        sched_port = free_port()
        sched = start_scheduler(procs, seed_rpc, seed_up, sched_port)
        sched_addr = f"127.0.0.1:{sched_port}"

        sock = str(tmp_path / "l.sock")
        start_daemon(procs, tmp_path, "leech",
                     {"unix_sock": sock,
                      "scheduler": {"addresses": [sched_addr]}})
        out = str(tmp_path / "l.out")
        pull = dfget(sock, url, out)
        time.sleep(4)                    # mid-download
        sched.kill()                     # control plane gone
        time.sleep(2)
        start_scheduler(procs, seed_rpc, seed_up, sched_port)  # back
        rc = pull.wait(timeout=120)
        assert rc == 0, pull.stderr.read()[-1500:]
        assert open(out, "rb").read() == blob
    finally:
        teardown(procs)


def test_stream_survives_parent_death(tmp_path):
    """Ordered streaming through the daemon proxy while a parent dies:
    the byte stream must arrive complete and in order (reference
    peertask_stream_resume_test.go re-homes a stream mid-read)."""
    blob = os.urandom(48 << 20)
    data = tmp_path / "blob.bin"
    data.write_bytes(blob)
    procs = []
    try:
        origin_port = start_origin(procs, str(data), 4.0)
        url = f"http://127.0.0.1:{origin_port}/blobs/sha256:{'0' * 64}"
        # the origin serves any path; the blob-shaped path routes via P2P
        seed_rpc, seed_up = free_port(), free_port()
        start_daemon(procs, tmp_path, "seed",
                     {"is_seed": True, "rpc_port": seed_rpc,
                      "upload": {"port": seed_up}})
        sched_port = free_port()
        start_scheduler(procs, seed_rpc, seed_up, sched_port)
        sched_addr = f"127.0.0.1:{sched_port}"

        # peer-a warms the task so it becomes the stream's parent
        sock_a = str(tmp_path / "a.sock")
        pa = start_daemon(procs, tmp_path, "peer-a",
                          {"unix_sock": sock_a,
                           "scheduler": {"addresses": [sched_addr]}})
        pull_a = dfget(sock_a, url, str(tmp_path / "a.out"))

        proxy_port = free_port()
        start_daemon(procs, tmp_path, "streamer",
                     {"scheduler": {"addresses": [sched_addr]},
                      "proxy": {"enabled": True, "port": proxy_port}})
        time.sleep(3)
        req = urllib.request.Request(
            url, headers={"Accept": "application/octet-stream"})
        req.set_proxy(f"127.0.0.1:{proxy_port}", "http")
        got = bytearray()
        killed = False
        with urllib.request.urlopen(req, timeout=180) as resp:
            while True:
                chunk = resp.read(1 << 20)
                if not chunk:
                    break
                got += chunk
                if not killed and len(got) > len(blob) // 3:
                    pa.kill()            # parent dies mid-stream
                    pull_a.kill()
                    killed = True
        assert killed, "stream finished before the kill - pace the origin"
        assert bytes(got) == blob
    finally:
        teardown(procs)


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
