"""Priority plumbing: manager applications -> scheduler arbitration -> GC.

VERDICT r04 missing #3 / next #7. Reference:
``manager/models/application.go:24`` (priority per application),
``scheduler/resource/peer.go:486 CalculatePriority`` (explicit > application
> default), ``scheduler/service/service_v2.go:1318`` (LEVEL1 forbidden,
LEVEL2 straight to origin), and priority-ordered storage eviction.
Our arbitration is admission-side: the scheduler-wide back-source budget is
counted per priority class, so a LEVEL0 request is admitted while LEVEL6
holders have the budget "full" — the implementable form of a LEVEL0 task
preempting a LEVEL6 task's back-source slot (origin pulls cannot be revoked
mid-flight).
"""

import asyncio

import pytest

from dragonfly2_tpu.common.errors import Code, DFError
from dragonfly2_tpu.idl.messages import (Host, HostType, Priority,
                                         RegisterPeerTaskRequest, UrlMeta)
from dragonfly2_tpu.scheduler.config import SchedulerConfig
from dragonfly2_tpu.scheduler.evaluator import Evaluator
from dragonfly2_tpu.scheduler.resource import PeerState, Resource
from dragonfly2_tpu.scheduler.scheduling import Scheduling
from dragonfly2_tpu.scheduler.seed_client import SeedPeerClient
from dragonfly2_tpu.scheduler.service import SchedulerService
from dragonfly2_tpu.scheduler.topology_store import TopologyStore


def _service(**cfg_kw) -> SchedulerService:
    cfg = SchedulerConfig(**cfg_kw)
    res = Resource()
    svc = SchedulerService(cfg, res, Scheduling(cfg, Evaluator()),
                           SeedPeerClient(res, []), TopologyStore())
    return svc


def _register(svc, task_no: int, peer_no: int, meta: UrlMeta):
    req = RegisterPeerTaskRequest(
        task_id=f"{task_no:064d}", url=f"http://o/f{task_no}",
        peer_id=f"peer-{task_no}-{peer_no}", url_meta=meta,
        peer_host=Host(id=f"h{task_no}-{peer_no}", ip="127.0.0.1",
                       port=1, download_port=2, type=HostType.NORMAL))
    return req


class TestResolution:
    def test_explicit_beats_application_beats_default(self):
        svc = _service()
        svc.applications = {"batch": 6, "critical": 0}
        # explicit value wins
        assert svc._resolve_priority(UrlMeta(
            priority=Priority.LEVEL2, application="batch")) == 2
        # LEVEL0 (unset) falls through to the application table
        assert svc._resolve_priority(UrlMeta(application="batch")) == 6
        # unknown application -> LEVEL0 (best class, reference behavior)
        assert svc._resolve_priority(UrlMeta(application="nope")) == 0
        assert svc._resolve_priority(UrlMeta()) == 0


class TestBackSourceArbitration:
    def test_level0_preempts_level6_back_source_budget(self):
        async def main():
            svc = _service(back_source_total=1, back_source_concurrent=4)
            svc.applications = {"batch": 6, "critical": 0}

            # LEVEL6 task's peer takes the one global slot
            a = await svc.register_peer_task(
                _register(svc, 1, 1, UrlMeta(application="batch")), None)
            peer_a = svc.resource.find_peer(a.task_id, "peer-1-1")
            assert peer_a.priority == 6
            # resolved priority is echoed to the daemon (storage GC reads it)
            assert int(a.resolved_priority) == 6
            pkt = svc._rule_back_source(peer_a)
            assert pkt.code == int(Code.SCHED_NEED_BACK_SOURCE)
            assert peer_a.state == PeerState.BACK_SOURCE

            # another LEVEL6 task: budget full for its class -> busy
            b = await svc.register_peer_task(
                _register(svc, 2, 1, UrlMeta(application="batch")), None)
            peer_b = svc.resource.find_peer(b.task_id, "peer-2-1")
            pkt = svc._rule_back_source(peer_b)
            assert pkt.code == int(Code.SCHED_TASK_STATUS_ERROR)
            assert peer_b.state != PeerState.BACK_SOURCE

            # LEVEL0 task: the LEVEL6 holder does not count against it —
            # admitted despite the "full" budget (slot preemption)
            c = await svc.register_peer_task(
                _register(svc, 3, 1, UrlMeta(application="critical")), None)
            peer_c = svc.resource.find_peer(c.task_id, "peer-3-1")
            assert peer_c.priority == 0
            pkt = svc._rule_back_source(peer_c)
            assert pkt.code == int(Code.SCHED_NEED_BACK_SOURCE)
            assert peer_c.state == PeerState.BACK_SOURCE

        asyncio.run(main())

    def test_level1_register_forbidden(self):
        async def main():
            svc = _service()
            with pytest.raises(DFError) as exc:
                await svc.register_peer_task(
                    _register(svc, 4, 1,
                              UrlMeta(priority=Priority.LEVEL1)), None)
            assert exc.value.code == Code.SCHED_FORBIDDEN

        asyncio.run(main())

    def test_level2_skips_p2p_patience(self):
        async def main():
            svc = _service()
            await svc.register_peer_task(
                _register(svc, 5, 1, UrlMeta(priority=Priority.LEVEL2)),
                None)
            peer = svc.resource.find_peer(f"{5:064d}", "peer-5-1")
            sink: asyncio.Queue = asyncio.Queue()
            peer.packet_sink = sink
            await asyncio.wait_for(
                svc._schedule_with_patience(peer, sink), timeout=1.0)
            pkt = sink.get_nowait()
            assert pkt.code == int(Code.SCHED_NEED_BACK_SOURCE)

        asyncio.run(main())


class TestManagerFeed:
    def test_applications_rpc_roundtrip(self, tmp_path):
        async def main():
            from dragonfly2_tpu.manager.service import ManagerService
            from dragonfly2_tpu.manager.store import Store

            store = Store(str(tmp_path / "m.db"))
            store.upsert_application("batch", url="http://batch",
                                     priority={"value": 6})
            store.upsert_application("critical", priority={"value": 0})
            svc = ManagerService(store)
            resp = await svc.list_applications(None, None)
            table = {e.name: int(e.priority) for e in resp.applications}
            assert table == {"batch": 6, "critical": 0}

        asyncio.run(main())


class TestGCOrdering:
    def test_low_priority_evicted_first(self, tmp_path):
        from dragonfly2_tpu.storage.manager import StorageConfig, StorageManager
        from dragonfly2_tpu.storage.metadata import TaskMetadata

        mgr = StorageManager(StorageConfig(
            data_dir=str(tmp_path), capacity_bytes=3_000_000,
            disk_gc_high_ratio=0.5, disk_gc_low_ratio=0.4,
            task_ttl_s=3600))
        # DISTINCT payloads: identical bytes would hardlink-coalesce in
        # the content store (physical usage 1 MB, under the watermark) and
        # nothing would need evicting — this test is about priority ORDER
        for i, prio in enumerate([0, 6]):
            payload = bytes([ord("a") + i]) * 1_000_000
            md = TaskMetadata(task_id=f"{i:064x}", url=f"http://o/{i}",
                              content_length=len(payload),
                              total_piece_count=1, piece_size=len(payload),
                              priority=prio)
            ts = mgr.register_task(md)
            ts.write_piece(0, 0, payload)
            ts.mark_done(success=True)
        assert mgr.try_gc() >= 1
        kept = [ts.md.priority for ts in mgr.tasks()]
        assert 0 in kept and 6 not in kept, \
            f"GC must evict the LEVEL6 task first, kept priorities {kept}"


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
