"""Download concurrency e2e: many simultaneous clients through one proxy.

Reference ``test/e2e/concurrency_test.go`` hammers the daemon proxy with
ApacheBench at -c 100/200/500/1000 and requires every request to succeed.
Same shape here: N concurrent HTTP clients fetch a blob-routed URL through
one daemon's proxy; the first request creates the task (back-source), the
rest join the running conductor's ordered stream or the completed-task
replay — every response must be byte-identical. This stresses the proxy's
connection handling, the piece broker's subscriber fan-out, and the
storage reuse path under contention.
"""

import asyncio
import hashlib
import os

import pytest

from dragonfly2_tpu.daemon.config import (DaemonConfig, ProxyConfig,
                                          StorageSection)
from dragonfly2_tpu.daemon.daemon import Daemon

from test_daemon_e2e import start_origin

BLOB = os.urandom(256 * 1024)
DIGEST = hashlib.sha256(BLOB).hexdigest()
PATH = f"blobs/sha256:{DIGEST}"          # blob-shaped: rides the P2P path


class TestProxyConcurrency:
    @pytest.mark.parametrize("concurrency,total",
                             [(100, 200), (200, 400), (500, 1000)])
    def test_concurrent_proxy_downloads(self, tmp_path, concurrency, total):
        async def main():
            import aiohttp

            origin, base = await start_origin({PATH: BLOB})
            daemon = Daemon(DaemonConfig(
                workdir=str(tmp_path / f"d{concurrency}"),
                host_ip="127.0.0.1", hostname="proxyd",
                storage=StorageSection(gc_interval_s=3600),
                proxy=ProxyConfig(enabled=True)))
            await daemon.start()
            try:
                proxy = f"http://127.0.0.1:{daemon.proxy_server.port}"
                url = f"{base}/{PATH}"
                sem = asyncio.Semaphore(concurrency)
                ok = {"n": 0}

                async def fetch(session: aiohttp.ClientSession) -> None:
                    async with sem:
                        async with session.get(url, proxy=proxy) as resp:
                            assert resp.status == 200, resp.status
                            body = await resp.read()
                    assert hashlib.sha256(body).hexdigest() == DIGEST
                    ok["n"] += 1

                conn = aiohttp.TCPConnector(limit=concurrency + 50)
                async with aiohttp.ClientSession(connector=conn) as s:
                    await asyncio.gather(*[fetch(s) for _ in range(total)])
                assert ok["n"] == total
            finally:
                await daemon.stop()
                await origin.cleanup()

        asyncio.run(main())


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
