"""ShardPrefetcher: shard URLs -> device arrays, overlapped with training.

BASELINE config #4's user-facing surface (WebDataset/TFRecord shards
prefetched into device memory during JAX training) at test scale on the
8-device CPU mesh: ordered delivery, byte fidelity, structural overlap
(later shards fetch while earlier ones are consumed), streamed-through
storage (pieces dropped after handoff), and the sync facade a training
loop actually calls.
"""

import asyncio
import hashlib
import os
import threading

import numpy as np
import pytest
from aiohttp import web

from dragonfly2_tpu.daemon.config import DaemonConfig, StorageSection
from dragonfly2_tpu.daemon.daemon import Daemon
from dragonfly2_tpu.tpu.data import ShardPrefetcher

SHARDS = [os.urandom(512 * 1024 + 17 * i) for i in range(4)]


async def _origin():
    hits = {"started": 0}

    async def handle(request: web.Request):
        i = int(request.path.rsplit("-", 1)[-1].split(".")[0])
        data = SHARDS[i]
        rng = request.headers.get("Range")
        if request.method == "HEAD" or rng is None:
            if request.method == "GET":
                hits["started"] += 1
            return web.Response(body=b"" if request.method == "HEAD" else data,
                                headers={"Accept-Ranges": "bytes",
                                         "Content-Length": str(len(data))})
        from dragonfly2_tpu.common.piece import parse_http_range
        r = parse_http_range(rng, len(data))
        if r.start == 0:
            hits["started"] += 1
        return web.Response(status=206, body=data[r.start:r.end], headers={
            "Content-Range": f"bytes {r.start}-{r.end - 1}/{len(data)}"})

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", handle)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}", hits


def _reassemble(arrays) -> bytes:
    flat = np.concatenate([np.asarray(a) for a in arrays])
    return flat.tobytes()


class TestShardPrefetcher:
    def test_ordered_bytes_and_streamed_through_storage(self, tmp_path):
        async def main():
            origin, base, hits = await _origin()
            daemon = Daemon(DaemonConfig(
                workdir=str(tmp_path / "d"), host_ip="127.0.0.1",
                hostname="pf", storage=StorageSection(gc_interval_s=3600)))
            await daemon.start()
            try:
                urls = [f"{base}/shard-{i}.tar" for i in range(4)]
                pf = ShardPrefetcher(daemon, urls, depth=2)
                out = []
                async for arrays in pf.astream():
                    out.append(_reassemble(arrays))
                assert len(out) == 4
                for i, got in enumerate(out):
                    assert got[:len(SHARDS[i])] == SHARDS[i], f"shard {i}"
                # streamed-through: pieces dropped after handoff
                assert not [t for t in daemon.ptm.storage_mgr.tasks()
                            if t.md.done], "shards must not accumulate"
            finally:
                await daemon.stop()
                await origin.cleanup()

        asyncio.run(main())

    def test_prefetch_overlaps_consumption(self, tmp_path):
        async def main():
            origin, base, hits = await _origin()
            daemon = Daemon(DaemonConfig(
                workdir=str(tmp_path / "d"), host_ip="127.0.0.1",
                hostname="pf2", storage=StorageSection(gc_interval_s=3600)))
            await daemon.start()
            try:
                urls = [f"{base}/shard-{i}.tar" for i in range(4)]
                pf = ShardPrefetcher(daemon, urls, depth=2)
                stream = pf.astream()
                first = await anext(stream)
                assert _reassemble(first)[:len(SHARDS[0])] == SHARDS[0]
                # structural overlap: without consuming shard 1, its fetch
                # (and shard 2's, depth=2) already hit the origin
                for _ in range(100):
                    if hits["started"] >= 2:
                        break
                    await asyncio.sleep(0.02)
                assert hits["started"] >= 2, (
                    f"no prefetch while consuming: {hits}")
                rest = [x async for x in stream]
                assert len(rest) == 3
            finally:
                await daemon.stop()
                await origin.cleanup()

        asyncio.run(main())

    def test_skip_failed_yields_the_rest(self, tmp_path):
        """A 404ing shard with ``skip_failed=True`` is logged and
        skipped; the healthy shards still arrive in order (dataset
        loaders routinely tolerate a missing shard). Without the flag
        the failure raises at the consuming step."""
        async def main():
            origin, base, _hits = await _origin()
            daemon = Daemon(DaemonConfig(
                workdir=str(tmp_path / "d"), host_ip="127.0.0.1",
                hostname="pf4", storage=StorageSection(gc_interval_s=3600)))
            await daemon.start()
            try:
                urls = [f"{base}/shard-0.tar",
                        f"{base}/missing/shard-9.tar",   # 500s at origin
                        f"{base}/shard-2.tar"]
                pf = ShardPrefetcher(daemon, urls, depth=2,
                                     skip_failed=True)
                out = [_reassemble(a) async for a in pf.astream()]
                assert len(out) == 2
                assert out[0][:len(SHARDS[0])] == SHARDS[0]
                assert out[1][:len(SHARDS[2])] == SHARDS[2]
                strict = ShardPrefetcher(daemon,
                                         [f"{base}/missing/shard-9.tar"])
                with pytest.raises(Exception):
                    async for _ in strict.astream():
                        pass
            finally:
                await daemon.stop()
                await origin.cleanup()

        asyncio.run(main())

    def test_early_consumer_exit_cancels_inflight(self, tmp_path):
        """Breaking out of astream() mid-epoch unwinds the in-flight
        prefetch tasks (the finally's cancel+gather) instead of leaking
        them — a training loop that stops at step N must not leave
        depth fetches running forever."""
        async def main():
            origin, base, _hits = await _origin()
            daemon = Daemon(DaemonConfig(
                workdir=str(tmp_path / "d"), host_ip="127.0.0.1",
                hostname="pf5", storage=StorageSection(gc_interval_s=3600)))
            await daemon.start()
            try:
                urls = [f"{base}/shard-{i}.tar" for i in range(4)]
                pf = ShardPrefetcher(daemon, urls, depth=2)
                stream = pf.astream()
                first = await anext(stream)
                assert _reassemble(first)[:len(SHARDS[0])] == SHARDS[0]
                await stream.aclose()          # early exit at step 1
                # the daemon still serves new work afterwards (nothing
                # wedged on the cancelled fetches)
                pf2 = ShardPrefetcher(daemon, [urls[3]])
                out = [_reassemble(a) async for a in pf2.astream()]
                assert out[0][:len(SHARDS[3])] == SHARDS[3]
            finally:
                await daemon.stop()
                await origin.cleanup()

        asyncio.run(main())

    def test_second_epoch_reuses_storage_with_fresh_ingest(self, tmp_path):
        """delete_after=False + a second epoch: the completed-task fast
        path has no conductor/sink, so the prefetcher must rebuild the
        device leg from stored pieces — NOT hand back epoch 1's consumed
        (possibly donated) arrays, and not error."""
        async def main():
            origin, base, hits = await _origin()
            daemon = Daemon(DaemonConfig(
                workdir=str(tmp_path / "d"), host_ip="127.0.0.1",
                hostname="pf4", storage=StorageSection(gc_interval_s=3600)))
            await daemon.start()
            try:
                urls = [f"{base}/shard-{i}.tar" for i in range(2)]
                for epoch in range(2):
                    pf = ShardPrefetcher(daemon, urls, depth=2,
                                         delete_after=False)
                    out = [_reassemble(a) async for a in pf.astream()]
                    for i, got in enumerate(out):
                        assert got[:len(SHARDS[i])] == SHARDS[i], \
                            f"epoch {epoch} shard {i}"
                # epoch 2 came from local storage, not the origin again
                assert hits["started"] == 2, hits
            finally:
                await daemon.stop()
                await origin.cleanup()

        asyncio.run(main())

    def test_sync_facade_from_training_thread(self, tmp_path):
        """The arrangement a real training loop uses: daemon's asyncio
        loop in a background thread, synchronous iteration in the caller."""
        boot: dict = {}
        ready = threading.Event()
        stop = threading.Event()

        def daemon_thread():
            async def main():
                origin, base, _h = await _origin()
                daemon = Daemon(DaemonConfig(
                    workdir=str(tmp_path / "d"), host_ip="127.0.0.1",
                    hostname="pf3",
                    storage=StorageSection(gc_interval_s=3600)))
                await daemon.start()
                boot["daemon"] = daemon
                boot["base"] = base
                boot["loop"] = asyncio.get_running_loop()
                ready.set()
                while not stop.is_set():
                    await asyncio.sleep(0.05)
                await daemon.stop()
                await origin.cleanup()

            asyncio.run(main())

        t = threading.Thread(target=daemon_thread, daemon=True)
        t.start()
        assert ready.wait(timeout=60)
        try:
            urls = [f"{boot['base']}/shard-{i}.tar" for i in range(3)]
            pf = ShardPrefetcher(boot["daemon"], urls, depth=2,
                                 loop=boot["loop"])
            got = [_reassemble(a) for a in pf]
            assert len(got) == 3
            for i, g in enumerate(got):
                assert g[:len(SHARDS[i])] == SHARDS[i]
        finally:
            stop.set()
            t.join(timeout=30)

    def test_duplicate_urls_serialize_not_corrupt(self, tmp_path):
        """Sampling with replacement: the same URL twice with depth=2 must
        yield two valid copies, never a shared consumed sink."""
        async def main():
            origin, base, hits = await _origin()
            daemon = Daemon(DaemonConfig(
                workdir=str(tmp_path / "d"), host_ip="127.0.0.1",
                hostname="pf5", storage=StorageSection(gc_interval_s=3600)))
            await daemon.start()
            try:
                url = f"{base}/shard-0.tar"
                pf = ShardPrefetcher(daemon, [url, url], depth=2)
                out = [_reassemble(a) async for a in pf.astream()]
                assert len(out) == 2
                for got in out:
                    assert got[:len(SHARDS[0])] == SHARDS[0]
            finally:
                await daemon.stop()
                await origin.cleanup()

        asyncio.run(main())

    def test_sync_without_loop_raises(self, tmp_path):
        pf = ShardPrefetcher(None, [])
        with pytest.raises(RuntimeError):
            iter(pf).__next__()


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
