"""Swarm immune system: pod-wide peer quarantine with corruption-source
attribution and byzantine chaos (docs/RESILIENCE.md "Quarantine ladder").

Units: the daemon verdict ledger (typed verdicts, decay, the
anti-slander rule), the scheduler quarantine registry (ladder walk,
probation probe budget, self-flag), the scheduling filter's
``quarantined`` exclusion, and podscope's poisoner-offered breach.

Chaos e2e (acceptance): an 8-daemon swarm (seed + poisoner + 6 leechers)
with the poisoner's ``upload.serve`` armed to corrupt every range it
serves — every pull completes byte-identical, the poisoner is
quarantined pod-wide after a bounded number of corrupt verdicts, wasted
corrupt transfers per downloader stay bounded, the rulings ride the
decision ledger, and once the fault is disarmed the host walks back
through probation to healthy without an operator.
"""

import asyncio
import os
import sys
import time

import pytest

from dragonfly2_tpu.common import faultgate
from dragonfly2_tpu.daemon.verdicts import VerdictLedger

sys.path.insert(0, os.path.dirname(__file__))

from test_daemon_e2e import daemon_config, start_origin  # noqa: E402
from test_scheduler import download_via, leecher_config  # noqa: E402


@pytest.fixture(autouse=True)
def _disarm():
    faultgate.reset()
    yield
    faultgate.reset()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


# ----------------------------------------------------------------------
# daemon/verdicts.py: the local verdict ledger
# ----------------------------------------------------------------------

class TestVerdictLedger:
    def test_corrupt_verdicts_flip_to_shunned_once(self):
        v = VerdictLedger(clock=FakeClock())
        assert not v.record("10.0.0.5:8000", "corrupt")
        assert not v.shunned("10.0.0.5:8000")          # below threshold
        assert v.deprioritized("10.0.0.5:8000")        # but suspect
        assert v.record("10.0.0.5:8000", "corrupt")    # the flip, once
        assert v.shunned("10.0.0.5:8000")
        assert not v.record("10.0.0.5:8000", "corrupt")  # already flipped
        assert v.shunned_addrs() == ["10.0.0.5:8000"]

    def test_soft_codes_never_shun(self):
        v = VerdictLedger(clock=FakeClock())
        for code in ("stall", "timeout", "refused"):
            for _ in range(20):
                assert not v.record("10.0.0.6:8000", code)
        assert not v.shunned("10.0.0.6:8000")

    def test_evidence_decays_back_to_clean(self):
        clk = FakeClock()
        v = VerdictLedger(halflife_s=10.0, clock=clk)
        v.record("a:1", "corrupt")
        v.record("a:1", "corrupt")
        assert v.shunned("a:1")
        clk.t += 120.0                 # 12 half-lives: evidence ~0
        assert not v.shunned("a:1")
        assert not v.deprioritized("a:1")

    def test_relayed_corruption_never_shuns_only_deprioritizes(self):
        """The relay-plane anti-slander rule: corruption that arrived
        over a parent's cut-through path is circumstantial (the bytes
        originated upstream of it) — however much accumulates, the
        relay is deprioritized, never shunned."""
        v = VerdictLedger(clock=FakeClock())
        for _ in range(50):
            v.record("relay:1", "corrupt", relayed=True)
        assert not v.shunned("relay:1")
        assert v.deprioritized("relay:1")
        v.record("direct:1", "corrupt")
        v.record("direct:1", "corrupt")
        assert v.shunned("direct:1")

    def test_anti_slander_hints_only_deprioritize(self):
        """THE anti-slander rule: gossip accusations move a host to the
        back of the ordering and can NEVER shun it — however many arrive."""
        clk = FakeClock()
        v = VerdictLedger(clock=clk)
        for _ in range(100):
            v.hint("victim:9000")
        assert v.deprioritized("victim:9000")
        assert not v.shunned("victim:9000")
        clk.t += 1000.0                # hint TTL expired
        assert not v.deprioritized("victim:9000")

    def test_hint_plus_local_verdict_still_requires_local_threshold(self):
        v = VerdictLedger(clock=FakeClock())
        v.hint("x:1")
        assert not v.record("x:1", "corrupt")   # 1 local + hints != shun
        assert not v.shunned("x:1")
        assert v.record("x:1", "corrupt")       # the second LOCAL verdict
        assert v.shunned("x:1")

    def test_self_quarantine_is_sticky_and_snapshotted(self):
        v = VerdictLedger(clock=FakeClock())
        assert not v.self_quarantined
        v.self_quarantine("boot re-verify dropped 3 pieces")
        assert v.self_quarantined
        snap = v.snapshot()
        assert snap["self_quarantined"] is True
        assert "re-verify" in snap["self_reason"]

    def test_reoffense_after_decay_flips_again(self):
        """The flip is a threshold CROSSING, not a one-shot latch: a
        parent whose evidence decayed below the threshold and then
        re-offends must be severed (and journaled) AGAIN — a sticky
        first-flip flag silently disabled the response for relapses."""
        clk = FakeClock()
        v = VerdictLedger(halflife_s=10.0, clock=clk)
        assert not v.record("p:1", "corrupt")
        assert v.record("p:1", "corrupt")       # first crossing
        assert not v.record("p:1", "corrupt")   # already above: no re-flip
        clk.t += 120.0                          # evidence decays to ~0
        assert not v.shunned("p:1")
        assert not v.record("p:1", "corrupt")
        assert v.record("p:1", "corrupt")       # relapse: crossing AGAIN
        assert v.shunned("p:1")

    def test_hint_ledger_growth_is_bounded(self):
        """Forged gossip digests with fresh fake addresses every round
        must not grow the ledger without bound — and hearsay eviction
        never pushes out first-hand evidence."""
        clk = FakeClock()
        v = VerdictLedger(clock=clk)
        v.record("real:1", "corrupt")            # first-hand history
        for i in range(2 * VerdictLedger.MAX_PARENTS):
            clk.t += 0.01
            v.hint(f"fake{i}:1")
        assert len(v._parents) <= VerdictLedger.MAX_PARENTS
        assert "real:1" in v._parents


# ----------------------------------------------------------------------
# scheduler/quarantine.py: the pod-wide ladder
# ----------------------------------------------------------------------

class TestQuarantineRegistry:
    def _registry(self, clk, **kw):
        from dragonfly2_tpu.scheduler.quarantine import QuarantineRegistry
        rows = []
        reg = QuarantineRegistry(corrupt_threshold=3.0, halflife_s=600.0,
                                 probation_delay_s=5.0, probe_successes=2,
                                 probe_children=1, sink=rows.append,
                                 clock=clk, **kw)
        return reg, rows

    def test_ladder_walks_healthy_suspect_quarantined(self):
        clk = FakeClock()
        reg, rows = self._registry(clk)
        assert reg.state("h1") == "healthy"       # unknown: no state grown
        reg.record_corrupt("h1", task_id="t1", reporter="r1")
        assert reg.state("h1") == "suspect"
        assert reg.offerable("h1", "c1")
        reg.record_corrupt("h1", task_id="t1", reporter="r2")
        reg.record_corrupt("h1", task_id="t2", reporter="r1")
        assert reg.state("h1") == "quarantined"
        assert not reg.offerable("h1", "c1")
        assert [r["to_state"] for r in rows] == ["suspect", "quarantined"]
        # cross-task, cross-reporter evidence on the ruling row
        assert rows[-1]["tasks"] == 2
        assert sorted(rows[-1]["reporters"]) == ["r1", "r2"]

    def test_probation_probe_budget_and_reprieve(self):
        clk = FakeClock()
        reg, rows = self._registry(clk)
        for i in range(3):
            reg.record_corrupt("h1", task_id="t", reporter=f"r{i}")
        assert not reg.offerable("h1", "c1")
        clk.t += 5.1                               # probation delay
        assert reg.state("h1") == "probation"
        # bounded exposure: ONE probing child at a time
        assert reg.offerable("h1", "c1")
        assert not reg.offerable("h1", "c2")
        assert reg.offerable("h1", "c1")           # sticky for the prober
        reg.record_ok("h1")
        assert reg.state("h1") == "probation"      # 1 of 2 probes
        reg.record_ok("h1")
        assert reg.state("h1") == "healthy"        # reprieved, no operator
        assert rows[-1]["to_state"] == "healthy"
        assert reg.offerable("h1", "c2")

    def test_corrupt_during_probation_goes_straight_back(self):
        clk = FakeClock()
        reg, rows = self._registry(clk)
        for i in range(3):
            reg.record_corrupt("h1", reporter=f"r{i}")
        clk.t += 5.1
        assert reg.state("h1") == "probation"
        reg.record_corrupt("h1", reporter="probe-child")
        assert reg.state("h1") == "quarantined"
        clk.t += 4.9                # timer RESET: not yet probation again
        assert reg.state("h1") == "quarantined"
        clk.t += 0.2
        assert reg.state("h1") == "probation"

    def test_self_flag_quarantines_and_clearing_gives_probation(self):
        clk = FakeClock()
        reg, rows = self._registry(clk)
        reg.record_self("h2", True, reason="announce flag")
        assert reg.state("h2") == "quarantined"
        clk.t += 100.0              # self-flag never times into probation
        assert reg.state("h2") == "quarantined"
        reg.record_self("h2", False)
        assert reg.state("h2") == "probation"
        transitions = [r["to_state"] for r in rows]
        assert transitions == ["quarantined", "probation"]

    def test_snapshot_names_states(self):
        clk = FakeClock()
        reg, _rows = self._registry(clk)
        for i in range(3):
            reg.record_corrupt("bad-host", reporter=f"r{i}")
        snap = reg.snapshot()
        assert snap["hosts"]["bad-host"]["state"] == "quarantined"
        assert snap["hosts"]["bad-host"]["corrupt_evidence"] >= 3.0

    def test_single_reporter_cannot_quarantine(self):
        """The report-plane anti-slander rule: one faulty/byzantine
        CHILD forging corrupt reports tops a host out at suspect —
        eviction needs corroboration from a second reporter."""
        clk = FakeClock()
        reg, rows = self._registry(clk)
        for _ in range(20):
            reg.record_corrupt("victim", reporter="liar")
        assert reg.state("victim") == "suspect"
        assert reg.offerable("victim", "c1")
        reg.record_corrupt("victim", reporter="corroborator")
        assert reg.state("victim") == "quarantined"

    def test_relayed_evidence_suspects_but_never_quarantines(self):
        """The registry half of the relay anti-slander rule: a host
        accused only through cut-through transfers tops out at suspect —
        one direct-evidence threshold still quarantines as usual."""
        clk = FakeClock()
        reg, rows = self._registry(clk)
        for i in range(50):
            reg.record_corrupt("relay-host", relayed=True,
                               reporter=f"r{i}")
        assert reg.state("relay-host") == "suspect"
        assert reg.offerable("relay-host", "c1")
        snap = reg.snapshot()["hosts"]["relay-host"]
        assert snap["relayed_evidence"] >= 49.0
        assert snap["corrupt_evidence"] == 0.0
        # direct evidence still promotes normally on top
        for _ in range(3):
            reg.record_corrupt("relay-host")
        assert reg.state("relay-host") == "quarantined"


# ----------------------------------------------------------------------
# scheduling filter: the `quarantined` exclusion
# ----------------------------------------------------------------------

class TestFilterExclusion:
    def _cluster(self):
        from dragonfly2_tpu.idl.messages import Host as HostMsg
        from dragonfly2_tpu.idl.messages import HostType
        from dragonfly2_tpu.scheduler.resource import (PeerState, Resource,
                                                       Task)
        res = Resource()
        task = Task("t" + "0" * 63, "u://x")
        task.set_content_info(100 * 4, 4, 100)

        def peer(name, host_type=HostType.NORMAL):
            host = res.store_host(HostMsg(id=f"{name}-host", ip="1.2.3.4",
                                          port=1, download_port=2,
                                          type=host_type))
            p = res.get_or_create_peer(f"{name}-peer", task, host)
            p.transit(PeerState.RUNNING)
            return p

        return res, task, peer

    def test_quarantined_parent_excluded_with_reason(self):
        from dragonfly2_tpu.scheduler.config import SchedulerConfig
        from dragonfly2_tpu.scheduler.evaluator import Evaluator
        from dragonfly2_tpu.scheduler.quarantine import QuarantineRegistry
        from dragonfly2_tpu.scheduler.scheduling import Scheduling
        res, task, peer = self._cluster()
        good = peer("good")
        good.finished_pieces = set(range(100))
        bad = peer("bad")
        bad.finished_pieces = set(range(100))
        child = peer("child")
        reg = QuarantineRegistry(corrupt_threshold=1.0, min_reporters=1)
        sched = Scheduling(SchedulerConfig(), Evaluator(), quarantine=reg)
        rows = []
        sched.decision_sink = rows.append
        offer = sched.find_parents(child)
        assert {p.id for p in offer} == {"good-peer", "bad-peer"}
        reg.record_corrupt("bad-host")
        child.last_offer_ids = set()
        offer = sched.find_parents(child)
        assert {p.id for p in offer} == {"good-peer"}
        excluded = [e for r in rows for e in r.get("excluded") or []]
        assert any(e["reason"] == "quarantined"
                   and e["host_id"] == "bad-host" for e in excluded)

    def test_armed_empty_registry_changes_nothing(self):
        from dragonfly2_tpu.scheduler.config import SchedulerConfig
        from dragonfly2_tpu.scheduler.evaluator import Evaluator
        from dragonfly2_tpu.scheduler.quarantine import QuarantineRegistry
        from dragonfly2_tpu.scheduler.scheduling import Scheduling
        res, task, peer = self._cluster()
        a = peer("a")
        a.finished_pieces = set(range(100))
        child = peer("child")
        bare = Scheduling(SchedulerConfig(), Evaluator())
        armed = Scheduling(SchedulerConfig(), Evaluator(),
                           quarantine=QuarantineRegistry())
        assert [p.id for p in bare.find_parents(child)] \
            == [p.id for p in armed.find_parents(child)]

    def test_seed_election_skips_quarantined(self):
        from dragonfly2_tpu.scheduler.config import SeedPeerAddr
        from dragonfly2_tpu.scheduler.quarantine import QuarantineRegistry
        from dragonfly2_tpu.scheduler.resource import Resource
        from dragonfly2_tpu.scheduler.seed_client import SeedPeerClient
        reg = QuarantineRegistry(corrupt_threshold=1.0, min_reporters=1)
        seeds = [SeedPeerAddr(host_id=f"s{i}", ip="127.0.0.1", rpc_port=i)
                 for i in range(1, 4)]
        sc = SeedPeerClient(Resource(), seeds, quarantine=reg)
        first = sc._elect("task-x")
        reg.record_corrupt(first)
        second = sc._elect("task-x")
        assert second != first
        # every member quarantined: still elect someone (injection beats
        # no seed path at all)
        for s in seeds:
            reg.record_corrupt(s.host_id)
        assert sc._elect("task-x") in {s.host_id for s in seeds}


# ----------------------------------------------------------------------
# PEX: anti-slander over gossip + shunned holders dropped
# ----------------------------------------------------------------------

def _gossiper(verdicts, host_id="g1", port=1111):
    from dragonfly2_tpu.daemon.pex import PexGossiper
    from dragonfly2_tpu.idl.messages import Host

    class _Storage:
        def tasks(self):
            return []

    return PexGossiper(
        storage_mgr=_Storage(),
        host_info=lambda: Host(id=host_id, ip="127.0.0.1", port=port,
                               download_port=port),
        verdicts=verdicts)


class TestPexAntiSlander:
    def test_digest_carries_local_suspects_and_receiver_only_hints(self):
        clk = FakeClock()
        va = VerdictLedger(clock=clk)
        va.record("10.9.9.9:7000", "corrupt")
        va.record("10.9.9.9:7000", "corrupt")
        assert va.shunned("10.9.9.9:7000")
        ga = _gossiper(va, "a", 1111)
        digest = ga.build_digest()
        assert digest["suspects"] == ["10.9.9.9:7000"]

        vb = VerdictLedger(clock=FakeClock())
        gb = _gossiper(vb, "b", 2222)
        assert gb.ingest(ga.envelope())
        # the accused third party is deprioritized, NEVER shunned —
        # whatever the accuser's digest claims (unit + gossip-round form
        # of the anti-slander rule)
        assert vb.deprioritized("10.9.9.9:7000")
        assert not vb.shunned("10.9.9.9:7000")

    def test_repeated_slander_rounds_never_escalate(self):
        va = VerdictLedger(clock=FakeClock())
        va.record("10.9.9.9:7000", "corrupt")
        va.record("10.9.9.9:7000", "corrupt")
        ga = _gossiper(va, "a", 1111)
        vb = VerdictLedger(clock=FakeClock())
        gb = _gossiper(vb, "b", 2222)
        for _ in range(25):
            assert gb.ingest(ga.envelope())
        assert not vb.shunned("10.9.9.9:7000")
        # B's own rung would still OFFER the accused (last, not gone):
        # only B's first-hand verdicts may remove it
        assert vb.deprioritized("10.9.9.9:7000")

    def test_shunned_origin_claims_dropped_from_swarm_index(self):
        """A holder this daemon shunned first-hand stops being indexed
        (and prior claims go) — the pex rung cannot be steered back."""
        from dragonfly2_tpu.daemon.pex import PexGossiper
        from dragonfly2_tpu.idl.messages import Host

        class _Md:
            def __init__(self):
                self.task_id = "t" + "1" * 63
                self.pieces = {0: object()}
                self.total_piece_count = 2
                self.content_length = 8
                self.piece_size = 4
                self.done = False
                self.success = False

        class _Ts:
            md = _Md()

        class _Storage:
            def tasks(self):
                return [_Ts()]

        poisoner = PexGossiper(
            storage_mgr=_Storage(),
            host_info=lambda: Host(id="poison", ip="10.0.0.9", port=9,
                                   download_port=9999))
        vb = VerdictLedger(clock=FakeClock())
        gb = _gossiper(vb, "b", 2222)
        assert gb.ingest(poisoner.envelope())
        assert gb.index.tasks()                     # claim landed
        vb.record("10.0.0.9:9999", "corrupt")
        vb.record("10.0.0.9:9999", "corrupt")
        assert gb.ingest(poisoner.envelope())       # next round's digest
        assert not gb.index.tasks()                 # claims dropped

    def test_self_quarantined_daemon_advertises_no_tasks(self):
        from dragonfly2_tpu.daemon.pex import PexGossiper
        from dragonfly2_tpu.idl.messages import Host

        class _Md:
            task_id = "t" + "2" * 63
            pieces = {0: object()}
            total_piece_count = 1
            content_length = 4
            piece_size = 4
            done = True
            success = True

        class _Ts:
            md = _Md()

        class _Storage:
            def tasks(self):
                return [_Ts()]

        v = VerdictLedger(clock=FakeClock())
        g = PexGossiper(
            storage_mgr=_Storage(),
            host_info=lambda: Host(id="s", ip="127.0.0.1", port=1,
                                   download_port=1234),
            verdicts=v)
        assert g.build_digest()["tasks"]
        v.self_quarantine("rot")
        digest = g.build_digest()
        assert digest["tasks"] == []
        assert digest["origin"]["selfq"] is True


# ----------------------------------------------------------------------
# podscope: the poisoner-offered breach (dfdiag --pod exit 3)
# ----------------------------------------------------------------------

class TestPodscopeQuarantine:
    def _snap(self, addr, *, shunned=(), swarm_holders=(), selfq=False):
        return {
            "addr": addr, "flights": {}, "flight_index": {},
            "health": None,
            "pex": {"swarm": {"tasks": {
                "t1": [{"addr": a} for a in swarm_holders]}}},
            "verdicts": {
                "self_quarantined": selfq,
                "parents": {a: {"shunned": True, "codes": {"corrupt": 2}}
                            for a in shunned},
            },
        }

    def test_poisoner_still_offered_is_a_breach(self):
        from dragonfly2_tpu.common import podscope
        report = podscope.aggregate([
            self._snap("d1:1", shunned=["10.0.0.9:9999"]),
            self._snap("d2:1", swarm_holders=["10.0.0.9:9999"]),
        ])
        assert report["quarantine"]["shunned"] == {
            "10.0.0.9:9999": ["d1:1"]}
        assert report["quarantine"]["still_offered"] == {
            "10.0.0.9:9999": ["d2:1"]}
        assert any(b.startswith("poisoner_offered")
                   for b in report["breaches"])
        assert "quarantined" in report["verdict"] \
            or "shunned" in report["verdict"]

    def test_shunned_everywhere_is_no_breach(self):
        from dragonfly2_tpu.common import podscope
        report = podscope.aggregate([
            self._snap("d1:1", shunned=["10.0.0.9:9999"]),
            self._snap("d2:1"),
        ])
        assert not any(b.startswith("poisoner_offered")
                       for b in report["breaches"])

    def test_self_quarantined_named_in_verdict(self):
        from dragonfly2_tpu.common import podscope
        report = podscope.aggregate([self._snap("d1:1", selfq=True)])
        assert report["quarantine"]["self_quarantined"] == ["d1:1"]
        assert "SELF-QUARANTINED" in report["verdict"]


# ----------------------------------------------------------------------
# engine: the local flip severs the parent and journals `quarantine`
# ----------------------------------------------------------------------

class TestEngineShun:
    def test_note_corrupt_flip_journals_and_gates_admission(self):
        from dragonfly2_tpu.daemon import flight_recorder as fr
        from dragonfly2_tpu.daemon.flight_recorder import TaskFlight
        from dragonfly2_tpu.daemon.piece_engine import PieceEngine
        from dragonfly2_tpu.idl.messages import PieceInfo

        class _Conductor:
            flight = TaskFlight("t" * 64, "p" * 16)

        v = VerdictLedger(clock=FakeClock())
        eng = PieceEngine(verdicts=v)
        c = _Conductor()
        info = PieceInfo(piece_num=0, range_size=4096)
        assert not eng._note_corrupt(c, info, "bad-peer", addr="9.9.9.9:1")
        assert eng._note_corrupt(c, info, "bad-peer", addr="9.9.9.9:1")
        kinds = [e[1] for e in c.flight.events]
        assert kinds.count(fr.CORRUPT) == 2
        assert kinds.count(fr.QUARANTINE) == 1      # journaled ONCE
        summary = c.flight.summarize()
        assert summary["quarantined_parents"] == ["9.9.9.9:1"]
        assert summary["fail_codes"]["corrupt"] == 2
        # the admission gate now refuses the address, whoever offers it
        assert not eng._admissible("bad-peer", "9.9.9.9:1")
        assert eng._admissible("good-peer", "8.8.8.8:1")

    def test_relayed_corruption_never_flips_the_engine_gate(self):
        from dragonfly2_tpu.daemon.piece_engine import PieceEngine
        from dragonfly2_tpu.idl.messages import PieceInfo

        class _Conductor:
            flight = None

        v = VerdictLedger(clock=FakeClock())
        eng = PieceEngine(verdicts=v)
        info = PieceInfo(piece_num=0, range_size=4096)
        for _ in range(20):
            assert not eng._note_corrupt(_Conductor(), info, "relay-peer",
                                         addr="7.7.7.7:1", relayed=True)
        assert not v.shunned("7.7.7.7:1")
        assert eng._admissible("relay-peer", "7.7.7.7:1")


# ----------------------------------------------------------------------
# chaos e2e: the byzantine swarm (acceptance)
# ----------------------------------------------------------------------

class TestByzantineSwarmE2E:
    def test_poisoned_swarm_quarantines_completes_and_reprieves(
            self, tmp_path):
        """8-daemon swarm + 1 byzantine poisoner, end to end: byte-
        identical pulls, bounded corrupt waste, pod-wide quarantine via
        ledger-replayable rulings, anti-propagation, and the probation
        reprieve once the fault is disarmed."""
        from dragonfly2_tpu.daemon.daemon import Daemon
        from dragonfly2_tpu.scheduler.config import (SchedulerConfig,
                                                     SeedPeerAddr)
        from dragonfly2_tpu.scheduler.quarantine import (HEALTHY, PROBATION,
                                                         QUARANTINED)
        from dragonfly2_tpu.scheduler.server import Scheduler
        data = os.urandom(26 * 1024 * 1024 + 321)    # 7 pieces @ 4 MiB

        async def go():
            origin, base = await start_origin({"m.bin": data})
            url = f"{base}/m.bin"
            seed_cfg = daemon_config(tmp_path, "seed")
            seed_cfg.is_seed = True
            seed = Daemon(seed_cfg)
            await seed.start()
            sched = Scheduler(SchedulerConfig(
                quarantine_corrupt_threshold=3.0,
                quarantine_probation_delay_s=1.0,
                quarantine_probe_successes=1,
                seed_peers=[SeedPeerAddr(
                    ip="127.0.0.1", rpc_port=seed.rpc.port,
                    download_port=seed.upload_server.port)]))
            await sched.start()
            poison = Daemon(leecher_config(tmp_path, "poison",
                                           sched.address))
            await poison.start()
            leechers = []
            seed_stopped = False
            try:
                # phase 1: the poisoner pulls the task CLEAN and becomes
                # a complete, attractive parent
                r = await download_via(poison, url,
                                       str(tmp_path / "poison.out"))
                assert r is not None
                assert (tmp_path / "poison.out").read_bytes() == data
                poison_host = "poison-127.0.0.1"
                assert poison.upload_server.host_id == poison_host

                # phase 2: arm the byzantine fault — EVERY range this
                # one daemon serves gets a flipped byte (key-scoped so
                # the co-resident seed/leechers stay honest)
                faultgate.arm_script(
                    f"upload.serve@{poison_host}=corrupt:pct=100:n=-1")

                for i in range(1, 7):
                    d = Daemon(leecher_config(tmp_path, f"l{i}",
                                              sched.address))
                    await d.start()
                    leechers.append(d)
                outs = [str(tmp_path / f"l{i}.out") for i in range(1, 7)]
                results = await asyncio.gather(
                    *(download_via(d, url, out)
                      for d, out in zip(leechers, outs)))
                # every pull completed BYTE-IDENTICAL despite the poisoner
                assert all(r is not None for r in results)
                for out in outs:
                    assert open(out, "rb").read() == data, \
                        "a poisoned byte reached a landed file"

                # pod-wide quarantine engaged on bounded evidence (the
                # short test probation_delay may have already walked the
                # quiet host onward — the ledger rows below prove the
                # QUARANTINED ruling fired either way)
                reg = sched.quarantine
                assert reg is not None
                assert reg.state(poison_host) in (QUARANTINED, PROBATION)
                snap = reg.snapshot()["hosts"][poison_host]
                # bounded: each child's own ledger stops feeding after
                # ~2 verdicts plus whatever its 4 workers already had in
                # flight when the flip landed — O(children x (shun +
                # parallelism)), never one-per-piece-per-child-forever
                # (the unprotected regime: dfbench --pr12 quarantine_off)
                assert snap["corrupt_evidence"] <= 6 * 6.0, snap

                # wasted corrupt transfers per downloader stay bounded:
                # each child's own ledger shuns at 2, so nobody absorbed
                # more than a handful
                for d in leechers:
                    tid = results[0].task_id
                    flight = d.flight_recorder.get(tid)
                    if flight is None:
                        continue
                    s = flight.summarize()
                    absorbed = sum((s.get("corrupt_pieces") or {}).values())
                    # bound = local-shun threshold + one corrupt per
                    # in-flight worker racing the flip + a few relayed
                    # secondaries (siblings cut-through-relaying poisoned
                    # bytes they had not verified yet) — NEVER
                    # pieces x retries, which is what the unprotected
                    # fabric absorbs (dfbench --pr12 quarantine_off)
                    assert absorbed <= 12, (d.hostname, s["corrupt_pieces"])
                # the typed fail codes rode the summaries
                any_fail_codes = any(
                    (d.flight_recorder.get(results[0].task_id)
                     .summarize().get("fail_codes") or {}).get("corrupt")
                    for d in leechers
                    if d.flight_recorder.get(results[0].task_id))
                assert any_fail_codes

                # local plane: children that absorbed >= 2 corrupt
                # verdicts shunned the poisoner themselves (whether a
                # given child reaches 2 before the POD-wide exclusion
                # saves it is a dispatch race — the deterministic flip
                # semantics live in TestEngineShun); every local shun is
                # matched by a journaled `quarantine` flight event
                paddr = f"127.0.0.1:{poison.upload_server.port}"
                shunners = [d for d in leechers if d.verdicts.shunned(paddr)]
                for d in shunners:
                    flight = d.flight_recorder.get(results[0].task_id)
                    assert flight is not None
                    assert paddr in (flight.summarize()
                                     .get("quarantined_parents") or []), \
                        d.hostname
                # anti-propagation: an honest host is shunned by NOBODY
                # (gossip hints can only deprioritize)
                honest_addrs = {f"127.0.0.1:{d.upload_server.port}"
                                for d in leechers} | {
                    f"127.0.0.1:{seed.upload_server.port}"}
                for d in leechers:
                    for a in honest_addrs:
                        assert not d.verdicts.shunned(a), (d.hostname, a)

                # every ruling is on the decision ledger, replayable
                rows = [r for r in sched.ledger.snapshot(
                    limit=512)["decisions"]
                    if r.get("decision_kind") == "quarantine"]
                assert any(r["to_state"] == "quarantined" for r in rows)
                # ONLY the poisoner reaches quarantined: honest leechers
                # that cut-through-relayed poisoned bytes may pick up
                # half-weight `suspect` evidence (the relay attribution
                # rule) but must never be evicted for the poisoner's sins
                assert all(r["host_id"] == poison_host for r in rows
                           if r["to_state"] == "quarantined"), rows
                for d in [seed] + leechers:
                    hid = f"{d.hostname}-127.0.0.1"
                    assert reg.state(hid) in ("healthy", "suspect"), hid

                # phase 3: disarm, ride out probation, and let a fresh
                # child's clean probe pieces reprieve the host
                faultgate.reset()
                await asyncio.sleep(1.1)            # probation delay
                assert reg.state(poison_host) == PROBATION
                for d in leechers:
                    await d.stop()
                leechers.clear()
                # the seed leaves too: the poisoner becomes the ONLY
                # holder, so the fresh child's probe pull deterministically
                # exercises it (with the seed up, announcement races can
                # hand every piece to the seed and the probe never fires)
                await seed.stop()
                seed_stopped = True
                l7 = Daemon(leecher_config(tmp_path, "l7", sched.address))
                await l7.start()
                leechers.append(l7)
                r7 = await download_via(l7, url, str(tmp_path / "l7.out"))
                assert r7 is not None
                assert (tmp_path / "l7.out").read_bytes() == data
                for _ in range(100):
                    if reg.state(poison_host) == HEALTHY:
                        break
                    await asyncio.sleep(0.05)
                assert reg.state(poison_host) == HEALTHY, \
                    reg.snapshot()["hosts"]
                rows = [r for r in sched.ledger.snapshot(
                    limit=512)["decisions"]
                    if r.get("decision_kind") == "quarantine"]
                trail = [r["to_state"] for r in rows]
                assert trail[-2:] == ["probation", "healthy"], trail
            finally:
                for d in leechers:
                    await d.stop()
                await poison.stop()
                await sched.stop()
                if not seed_stopped:
                    await seed.stop()
                await origin.cleanup()

        asyncio.run(go())


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
