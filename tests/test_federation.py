"""Cross-pod federation: pod identity + classify, per-pod seed election,
the scheduler's cross-pod filter, dispatcher tier pinning, PEX pod
scoping + inter-pod summaries, feature-schema versioning, and the
podscope [dcn] tier marks. All in-process — no sockets."""

import pytest

from dragonfly2_tpu.idl.messages import Host as HostMsg
from dragonfly2_tpu.idl.messages import LinkType, TopologyInfo
from dragonfly2_tpu.tpu import topology
from dragonfly2_tpu.tpu.topology import (LINK_BANDWIDTH_SCORE,
                                         LINK_TIER_NAMES, classify, ici_hops,
                                         link_type, pod_id)


def topo(slice_name="", zone="", pod="", coords=None):
    return TopologyInfo(slice_name=slice_name, zone=zone, pod=pod,
                        ici_coords=coords)


class TestPodIdentity:
    def test_pod_derived_from_slice_identity(self):
        assert pod_id(topo(slice_name="v5p-256-s0")) == "v5p-256-s0"

    def test_explicit_pod_wins_over_slice(self):
        assert pod_id(topo(slice_name="s0", pod="pod-A")) == "pod-A"

    def test_no_topology_means_no_pod(self):
        # the detect() plain-DCN-peer fallback: no identity, never
        # restricted by the federation plane
        assert pod_id(None) == ""
        assert pod_id(topo()) == ""

    def test_pod_id_stable_across_reannounce(self):
        # pod id is a pure function of the announced coordinates — two
        # announce cycles of the same host must land in the same pod
        a1 = topo(slice_name="s0", zone="z", coords=(1, 2))
        a2 = topo(slice_name="s0", zone="z", coords=(1, 2))
        assert pod_id(a1) == pod_id(a2)
        from dragonfly2_tpu.scheduler.federation import PodFederation
        fed = PodFederation()
        fed.observe_host("h1", a1)
        first = dict(fed.describe()["pods"])
        fed.observe_host("h1", a2)          # re-announce: no-op
        assert fed.describe()["pods"] == first

    def test_detect_reads_df_pod_id(self, monkeypatch):
        monkeypatch.setenv("DF_POD_ID", "pod-env")
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        topology.detect.cache_clear()
        try:
            assert topology.detect().pod == "pod-env"
        finally:
            topology.detect.cache_clear()


class TestClassify:
    def test_same_host(self):
        c = classify(topo("s0", "z"), topo("s0", "z"), same_host=True)
        assert c.link == LinkType.LOCAL
        assert c.same_pod and c.dcn_hops == 0

    def test_same_pod_ici(self):
        c = classify(topo("s0", "z", coords=(0, 0)),
                     topo("s0", "z", coords=(2, 1)))
        assert c.link == LinkType.ICI
        assert c.same_pod and c.dcn_hops == 0
        assert c.ici == 3

    def test_cross_pod_same_zone_is_dcn(self):
        c = classify(topo("s0", "z"), topo("s1", "z"))
        assert c.link == LinkType.DCN
        assert not c.same_pod and c.dcn_hops == 1

    def test_cross_zone_is_wan(self):
        c = classify(topo("s0", "za"), topo("s1", "zb"))
        assert c.link == LinkType.WAN
        assert not c.same_pod and c.dcn_hops == 2

    def test_missing_topology_is_plain_wan_peer(self):
        # the topology.py detect() fallback: no coordinates at all
        c = classify(None, topo("s0", "z"))
        assert c.link == LinkType.WAN
        assert not c.same_pod
        assert c.dcn_hops == 2
        assert c.ici == 1 << 16

    def test_explicit_pod_groups_slices(self):
        # two slices grouped into one pod: the link is still DCN (bytes
        # ride the NIC) but the pod boundary is not crossed
        c = classify(topo("s0", "z", pod="P"), topo("s1", "z", pod="P"))
        assert c.link == LinkType.DCN
        assert c.same_pod and c.dcn_hops == 0

    def test_ici_hops_mismatched_dims_unknown(self):
        assert ici_hops(topo(coords=(1, 2)), topo(coords=(1, 2, 3))) \
            == 1 << 16


class TestTierOrderingPinned:
    """The satellite pin: the dispatcher's demand-side tiers, the
    evaluator's bandwidth scores, and the pinned ledger tier names must
    agree on the ordering LOCAL == ICI (same pod) < DCN (cross-pod) <
    WAN (cross-zone) — a disagreement would let the daemon prefer the
    exact links the scheduler is rationing."""

    def test_dispatcher_tiers_name_the_pod_boundary(self):
        from dragonfly2_tpu.daemon.piece_dispatcher import (LINK_TIER,
                                                            TIER_CROSS_POD,
                                                            TIER_CROSS_ZONE,
                                                            TIER_SAME_POD)
        assert LINK_TIER[LinkType.LOCAL] == TIER_SAME_POD
        assert LINK_TIER[LinkType.ICI] == TIER_SAME_POD
        assert LINK_TIER[LinkType.DCN] == TIER_CROSS_POD
        assert LINK_TIER[LinkType.WAN] == TIER_CROSS_ZONE
        assert TIER_SAME_POD < TIER_CROSS_POD < TIER_CROSS_ZONE

    def test_dispatcher_ranking_agrees_with_evaluator_scoring(self):
        from dragonfly2_tpu.daemon.piece_dispatcher import LINK_TIER
        links = [LinkType.LOCAL, LinkType.ICI, LinkType.DCN, LinkType.WAN]
        tiers = [LINK_TIER[lt] for lt in links]
        scores = [LINK_BANDWIDTH_SCORE[lt] for lt in links]
        # tiers ascend (worse) exactly while scores descend (worse)
        assert tiers == sorted(tiers)
        assert scores == sorted(scores, reverse=True)

    def test_ledger_tier_names_cover_every_link(self):
        assert set(LINK_TIER_NAMES) == set(LinkType)
        assert [LINK_TIER_NAMES[lt] for lt in
                (LinkType.LOCAL, LinkType.ICI, LinkType.DCN, LinkType.WAN)
                ] == ["local", "ici", "dcn", "wan"]


# --------------------------------------------------------------- election

class FakeQuarantine:
    def __init__(self, bad=()):
        self.bad = set(bad)

    def offerable(self, host_id, child_id=""):
        return host_id not in self.bad


class TestPodFederationElection:
    def make(self, members=8, **kw):
        from dragonfly2_tpu.scheduler.federation import PodFederation
        fed = PodFederation(**kw)
        for i in range(members):
            fed.observe_host(f"h{i}", topo("pod-0", "z"))
        return fed

    def test_election_deterministic_and_sticky(self):
        a = self.make().seeds_for("task-x", "pod-0")
        b = self.make().seeds_for("task-x", "pod-0")
        assert a == b and len(a) == 1
        fed = self.make()
        first = fed.seeds_for("task-x", "pod-0")
        assert fed.seeds_for("task-x", "pod-0") is first   # memoized

    def test_different_tasks_spread_over_the_ring(self):
        fed = self.make(members=16)
        seeds = {fed.seeds_for(f"task-{i}", "pod-0")[0] for i in range(32)}
        assert len(seeds) > 1     # hash-ring, not a fixed leader

    def test_quarantined_member_skipped(self):
        plain = self.make().seeds_for("task-x", "pod-0")[0]
        fed = self.make(quarantine=FakeQuarantine(bad=[plain]))
        assert fed.seeds_for("task-x", "pod-0")[0] != plain

    def test_wholly_quarantined_pod_still_elects(self):
        # every member bad: the hashed member serves anyway (the
        # SeedPeerClient._elect exhaustion semantics, shared walk)
        all_bad = FakeQuarantine(bad={f"h{i}" for i in range(8)})
        fed = self.make(quarantine=all_bad)
        assert fed.seeds_for("task-x", "pod-0")

    def test_exhausted_election_emits_once(self):
        # a wholly-quarantined pod re-walks to the same hashed members:
        # the memo must refresh SILENTLY, not flood the ledger at
        # per-candidate rate (seeds_for runs per allows()/note() call)
        rows = []
        all_bad = FakeQuarantine(bad={f"h{i}" for i in range(8)})
        fed = self.make(quarantine=all_bad, sink=rows.append)
        first = fed.seeds_for("task-x", "pod-0")
        for _ in range(5):
            assert fed.seeds_for("task-x", "pod-0") == first
        assert len(rows) == 1
        assert rows[0]["result"] == "exhausted"

    def test_exhaustion_and_recovery_both_journaled(self):
        # the TRANSITIONS are what operators need: healthy -> exhausted
        # (the pod knowingly routes through a quarantined seed) and the
        # recovery back — each exactly once, even when the seed LIST
        # never changes
        rows = []
        q = FakeQuarantine()
        fed = self.make(quarantine=q, sink=rows.append)
        fed.seeds_for("task-x", "pod-0")
        q.bad = {f"h{i}" for i in range(8)}
        fed.seeds_for("task-x", "pod-0")
        fed.seeds_for("task-x", "pod-0")
        q.bad = set()
        fed.seeds_for("task-x", "pod-0")
        fed.seeds_for("task-x", "pod-0")
        assert [r["result"] for r in rows] == \
            ["elected", "exhausted", "reelected"]

    def test_dead_seed_reelected(self):
        rows = []
        fed = self.make(sink=rows.append)
        first = fed.seeds_for("task-x", "pod-0")[0]
        fed.forget_host(first)
        second = fed.seeds_for("task-x", "pod-0")[0]
        assert second != first
        kinds = [(r["decision_kind"], r["result"]) for r in rows]
        assert ("federation", "elected") in kinds
        assert ("federation", "reelected") in kinds

    def test_seed_client_walks_the_same_ring(self):
        # the shared walk: origin-seed election skips quarantined seeds
        from dragonfly2_tpu.rpc.balancer import HashRing
        from dragonfly2_tpu.scheduler.federation import walk_ring
        ring = HashRing(["a", "b", "c"])
        plain = walk_ring(ring, "k", 3, None)
        assert plain == [ring.pick("k")]
        skipped = walk_ring(ring, "k", 3, FakeQuarantine(bad=[plain[0]]))
        assert skipped and skipped[0] != plain[0]


# ----------------------------------------------------- scheduling filter

def build_task(pods=2, per_pod=3):
    from dragonfly2_tpu.scheduler.resource import (Peer, PeerState, Resource,
                                                   Task)
    res = Resource()
    task = Task("fedtest" + "0" * 57, "bench://fed")
    task.set_content_info(4 << 20, 1 << 20, 4)
    peers = []
    for p in range(pods):
        for i in range(per_pod):
            t = topo(f"pod-{p}", "z", coords=(i, 0))
            host = res.store_host(HostMsg(
                id=f"p{p}w{i}-host", ip="10.0.0.1", port=1, download_port=2,
                topology=t))
            peer = res.get_or_create_peer(f"p{p}w{i}-peer", task, host)
            peer.transit(PeerState.RUNNING)
            peer.finished_pieces = {0, 1}
            peers.append(peer)
    return task, peers


class TestSchedulingCrossPod:
    def make_sched(self, federation):
        from dragonfly2_tpu.scheduler.config import SchedulerConfig
        from dragonfly2_tpu.scheduler.evaluator import make_evaluator
        from dragonfly2_tpu.scheduler.scheduling import Scheduling
        return Scheduling(SchedulerConfig(), make_evaluator("default"),
                          federation=federation)

    def make_fed(self, task, peers, seeds_per_pod=1):
        from dragonfly2_tpu.scheduler.federation import PodFederation
        fed = PodFederation(seeds_per_pod=seeds_per_pod)
        for peer in peers:
            fed.observe_host(peer.host.id, peer.host.msg.topology)
        return fed

    def test_member_offer_never_crosses_pods(self):
        task, peers = build_task()
        fed = self.make_fed(task, peers)
        sched = self.make_sched(fed)
        seeds = set(fed.seeds_for(task.id, "pod-0"))
        member = next(p for p in peers
                      if p.host.msg.topology.slice_name == "pod-0"
                      and p.host.id not in seeds)
        offer = sched.find_parents(member)
        assert offer
        for parent in offer:
            assert parent.host.msg.topology.slice_name == "pod-0"

    def test_pod_seed_may_cross(self):
        task, peers = build_task()
        fed = self.make_fed(task, peers)
        sched = self.make_sched(fed)
        seed_hid = fed.seeds_for(task.id, "pod-0")[0]
        seed = next(p for p in peers if p.host.id == seed_hid)
        offer = sched.find_parents(seed)
        assert any(p.host.msg.topology.slice_name == "pod-1"
                   for p in offer)

    def test_podless_host_never_restricted(self):
        from dragonfly2_tpu.scheduler.resource import PeerState
        task, peers = build_task()
        fed = self.make_fed(task, peers)
        sched = self.make_sched(fed)
        host = task.peers[peers[0].id].host.msg  # reuse resource via peer
        from dragonfly2_tpu.scheduler.resource import Resource
        # a plain-DCN peer (no topology): joins the task, gets offers
        res_host = peers[0].host.__class__(HostMsg(
            id="plain-host", ip="10.0.0.2", port=1, download_port=2,
            topology=None))
        from dragonfly2_tpu.scheduler.resource import Peer
        plain = Peer("plain-peer", task, res_host)
        task.add_peer(plain)
        plain.transit(PeerState.RUNNING)
        offer = sched.find_parents(plain)
        assert offer    # cross-pod exclusion never applies to it

    def test_cross_pod_exclusion_rides_the_ledger(self):
        from dragonfly2_tpu.scheduler.scheduling import EXCLUSION_REASONS
        assert "cross-pod" in EXCLUSION_REASONS
        task, peers = build_task()
        fed = self.make_fed(task, peers)
        sched = self.make_sched(fed)
        rows = []
        sched.decision_sink = rows.append
        seeds = set(fed.seeds_for(task.id, "pod-0"))
        member = next(p for p in peers
                      if p.host.msg.topology.slice_name == "pod-0"
                      and p.host.id not in seeds)
        sched.find_parents(member)
        row = rows[-1]
        assert any(e["reason"] == "cross-pod" for e in row["excluded"])
        assert row["federation"]["pod"] == "pod-0"
        assert row["federation"]["is_pod_seed"] is False
        assert row["federation"]["pod_seeds"] == sorted(seeds)
        # every candidate carries the pinned link tier term + the
        # pod-boundary flag (classify is shipped semantics, not test-ware)
        for cand in row["candidates"]:
            assert cand["link_tier"] in ("local", "ici", "dcn", "wan")
            assert cand["cross_pod"] is False   # offer is all in-pod

    def test_federation_none_is_exact_old_path(self):
        # same pool, no federation: cross-pod parents offered freely and
        # no federation note on the row
        task, peers = build_task()
        sched = self.make_sched(None)
        rows = []
        sched.decision_sink = rows.append
        member = peers[0]
        offer = sched.find_parents(member)
        assert any(p.host.msg.topology.slice_name == "pod-1"
                   for p in offer)
        assert "federation" not in rows[-1]


# ----------------------------------------------------------- PEX scoping

class _Md:
    def __init__(self, task_id, pieces, total, done):
        self.task_id = task_id
        self.pieces = pieces
        self.total_piece_count = total
        self.content_length = total * (1 << 20)
        self.piece_size = 1 << 20
        self.done = done
        self.success = done


class _Ts:
    def __init__(self, md):
        self.md = md


class _FakeStorage:
    def __init__(self, entries):
        self._entries = entries

    def tasks(self):
        return [_Ts(md) for md in self._entries]


def make_gossiper(pod="pod-0", tasks=(), ip="10.0.0.9", **kw):
    from dragonfly2_tpu.daemon.pex import PexGossiper
    host = HostMsg(id=f"{pod or 'plain'}-self", ip=ip, port=1,
                   download_port=9000,
                   topology=topo(pod, "z") if pod else None)
    return PexGossiper(storage_mgr=_FakeStorage(list(tasks)),
                       host_info=lambda: host, **kw)


class TestPexPodScope:
    def test_full_digests_stay_pod_scoped(self):
        g = make_gossiper()
        g.observe_peer(host_id="same", ip="10.0.0.2", download_port=1,
                       topology=topo("pod-0", "z"), direct=True)
        g.observe_peer(host_id="other", ip="10.0.0.3", download_port=1,
                       topology=topo("pod-1", "z"), direct=True)
        g.observe_peer(host_id="podless", ip="10.0.0.4", download_port=1,
                       direct=True)
        names = {p.host_id for p in g._targets()}
        assert "same" in names and "podless" in names
        assert "other" not in names     # full piece sets never cross pods

    def test_pod_scope_off_or_podless_host_targets_everyone(self):
        g = make_gossiper(pod="")
        g.observe_peer(host_id="other", ip="10.0.0.3", download_port=1,
                       topology=topo("pod-1", "z"), direct=True)
        assert {p.host_id for p in g._targets()} == {"other"}

    def test_summary_has_no_piece_sets(self):
        from dragonfly2_tpu.daemon.pex import unseal
        g = make_gossiper(tasks=[
            _Md("t-done" + "0" * 58, {0, 1, 2, 3}, 4, True),
            _Md("t-part" + "0" * 58, {0, 1}, 4, False)])
        body = unseal(g.summary_envelope())
        assert body["kind"] == "summary"
        assert body["peers"] == []      # no membership hearsay either
        for t in body["tasks"]:
            assert "pieces" not in t and "relay" not in t
        part = next(t for t in body["tasks"] if not t["done"])
        assert part["have"] == 2

    def test_summary_ingest_indexes_only_complete_holders(self):
        sender = make_gossiper(pod="pod-1", ip="10.0.0.8", tasks=[
            _Md("t-done" + "0" * 58, {0, 1, 2, 3}, 4, True),
            _Md("t-part" + "0" * 58, {0, 1}, 4, False)])
        receiver = make_gossiper(pod="pod-0")
        assert receiver.ingest(sender.summary_envelope(),
                               transport="summary")
        assert receiver.index.tasks() == ["t-done" + "0" * 58]
        entry = receiver.index.parents_for("t-done" + "0" * 58)[0]
        assert entry.done
        # partial cross-pod claims never plant coverage the pex rung
        # would park on
        assert receiver.index.parents_for("t-part" + "0" * 58) == []

    def test_candidates_prefer_pod_local_coverage(self):
        from dragonfly2_tpu.daemon.swarm_index import SwarmEntry

        class Cond:
            task_id = "t" + "0" * 63
            ready = set()

        g = make_gossiper()
        local = SwarmEntry(host_id="local", ip="10.0.0.2", rpc_port=1,
                           download_port=1, topology=topo("pod-0", "z"),
                           done=True)
        remote = SwarmEntry(host_id="remote", ip="10.0.0.3", rpc_port=1,
                            download_port=1, topology=topo("pod-1", "z"),
                            done=True)
        g.index.update(Cond.task_id, local)
        g.index.update(Cond.task_id, remote)
        # pod-local holder covers: never leave the pod
        assert [e.host_id for e in g._candidates(Cond())] == ["local"]
        g.index.forget_host("local")
        # no pod-local coverage: the cross-pod holder is the fallback
        assert [e.host_id for e in g._candidates(Cond())] == ["remote"]

    def test_shunned_local_holder_never_masks_cross_pod_fallback(self):
        # the shun filter runs BEFORE the pod-first coverage gate: a
        # poisoned in-pod holder must not both satisfy coverage and
        # discard the clean cross-pod fallback (which would push the
        # pull all the way to origin)
        from dragonfly2_tpu.daemon.swarm_index import SwarmEntry

        class Cond:
            task_id = "t" + "0" * 63
            ready = set()

        class Shun:
            def shunned(self, addr):
                return addr == "10.0.0.2:1"

            def deprioritized(self, addr):
                return False

        g = make_gossiper(verdicts=Shun())
        g.index.update(Cond.task_id, SwarmEntry(
            host_id="bad-local", ip="10.0.0.2", rpc_port=1,
            download_port=1, topology=topo("pod-0", "z"), done=True))
        g.index.update(Cond.task_id, SwarmEntry(
            host_id="clean-remote", ip="10.0.0.3", rpc_port=1,
            download_port=1, topology=topo("pod-1", "z"), done=True))
        assert [e.host_id for e in g._candidates(Cond())] \
            == ["clean-remote"]

    def test_lone_daemon_with_only_cross_pod_contacts_still_gossips(self):
        # a fresh pod's first daemon bootstrapped off another pod's seed
        # must not be isolated by the pod-scope filter
        g = make_gossiper()
        g.observe_peer(host_id="other", ip="10.0.0.3", download_port=1,
                       topology=topo("pod-1", "z"), direct=True)
        assert {p.host_id for p in g._targets()} == {"other"}
        # ...but the moment a pod-local peer appears, scope re-engages
        g.observe_peer(host_id="same", ip="10.0.0.2", download_port=1,
                       topology=topo("pod-0", "z"), direct=True)
        assert {p.host_id for p in g._targets()} == {"same"}

    def test_summary_partials_surfaced_on_receiver(self):
        sender = make_gossiper(pod="pod-1", ip="10.0.0.8", tasks=[
            _Md("t-part" + "0" * 58, {0, 1}, 4, False)])
        receiver = make_gossiper(pod="pod-0")
        assert receiver.ingest(sender.summary_envelope(),
                               transport="summary")
        partials = receiver.debug_snapshot()["federation_partials"]
        claims = partials["pod-1-self"]
        assert claims["tasks"]["t-part" + "0" * 58] == {"have": 2,
                                                        "total": 4}
        assert claims["age_s"] >= 0.0
        # a later summary with the task completed clears the claim
        sender2 = make_gossiper(pod="pod-1", ip="10.0.0.8", tasks=[
            _Md("t-part" + "0" * 58, {0, 1, 2, 3}, 4, True)])
        receiver.ingest(sender2.summary_envelope(), transport="summary")
        assert "pod-1-self" not in \
            receiver.debug_snapshot()["federation_partials"]

    def test_summary_partials_age_out(self):
        from dragonfly2_tpu.daemon.pex import FED_PARTIALS_TTL_S
        sender = make_gossiper(pod="pod-1", ip="10.0.0.8", tasks=[
            _Md("t-part" + "0" * 58, {0, 1}, 4, False)])
        receiver = make_gossiper(pod="pod-0")
        receiver.ingest(sender.summary_envelope(), transport="summary")
        # a dead pod seed's claim must not outlive the TTL (nor crowd
        # live seeds out of the cap)
        receiver.fed_partials["pod-1-self"]["at"] -= \
            FED_PARTIALS_TTL_S + 1
        assert receiver.debug_snapshot()["federation_partials"] == {}

    def test_topology_pod_survives_the_wire(self):
        from dragonfly2_tpu.daemon.pex import _topo_from_wire, _topo_to_wire
        t = topo("s0", "z", pod="pod-X", coords=(1, 2))
        assert _topo_from_wire(_topo_to_wire(t)).pod == "pod-X"


class TestEvictionHooks:
    """A host/task leaving the resource model must leave the federation
    view too — a GC'd (silently dead) pod seed must not keep winning
    elections it can never serve."""

    def test_resource_eviction_notifies_federation(self):
        from dragonfly2_tpu.scheduler.resource import Resource
        res = Resource(host_ttl_s=0.0, task_ttl_s=0.0, peer_ttl_s=0.0)
        gone_hosts, gone_tasks = [], []
        res.on_host_evict = gone_hosts.append
        res.on_task_evict = gone_tasks.append
        res.store_host(HostMsg(id="h1", ip="10.0.0.1", port=1,
                               download_port=2))
        res.get_or_create_task("t" + "0" * 63, "bench://x")
        res.gc()
        assert gone_hosts == ["h1"]
        assert gone_tasks == ["t" + "0" * 63]

    def test_leave_host_notifies_federation(self):
        from dragonfly2_tpu.scheduler.resource import Resource
        res = Resource()
        gone = []
        res.on_host_evict = gone.append
        res.store_host(HostMsg(id="h1", ip="10.0.0.1", port=1,
                               download_port=2))
        res.leave_host("h1")
        assert gone == ["h1"]


class TestGnnSchemaGate:
    def test_stale_node_dim_refused_at_bind(self):
        # a v1 blob (6 node features, no pod_id) must be refused at bind
        # time — not crash the evaluator hot path on first imputation
        import numpy as np

        from dragonfly2_tpu.trainer import params_io, serving
        stale = {"encode": {"w": np.zeros((6, 8), np.float32),
                            "b": np.zeros((8,), np.float32)}}
        blob = params_io.serialize_params(stale, {"model": "topology_gnn"})
        with pytest.raises(ValueError, match="stale model refused"):
            serving.make_gnn_impute(blob)


# ------------------------------------------------- features + podscope

class TestFeatureSchema:
    def test_parent_features_unchanged_for_pr8_rows(self):
        from dragonfly2_tpu.trainer import features
        assert features.FEATURE_DIM == 7
        assert features.FEATURE_SCHEMA_VERSION == 2
        assert features.NODE_FEATURES[-1] == "pod_id"

    def test_decision_outcome_rows_carry_tier_and_pod(self):
        from dragonfly2_tpu.trainer.features import decision_outcome_rows
        feats = [0.5] * 7
        rows = [
            {"kind": "decision", "decision_id": "d1", "task_id": "t",
             "peer_id": "c", "federation": {"pod": "pod-0"},
             "candidates": [{"peer_id": "p", "features": feats,
                             "rank": 1, "link_tier": "ici"}]},
            {"kind": "piece", "decision_id": "d1", "parent_peer_id": "p",
             "label": 0.7},
            # a v1 row (no tier/federation) must still parse
            {"kind": "decision", "decision_id": "d2", "task_id": "t",
             "peer_id": "c",
             "candidates": [{"peer_id": "q", "features": feats,
                             "rank": 1}]},
            {"kind": "piece", "decision_id": "d2", "parent_peer_id": "q",
             "label": 0.5},
        ]
        out = {r["decision_id"]: r for r in decision_outcome_rows(rows)}
        assert out["d1"]["link_tier"] == "ici"
        assert out["d1"]["pod"] == "pod-0"
        assert out["d2"]["link_tier"] == "" and out["d2"]["pod"] == ""

    def test_node_row_includes_pod(self):
        from dragonfly2_tpu.trainer.features import topology_to_graph
        g = topology_to_graph(
            [{"src": "a", "dst": "b", "avg_rtt_us": 100.0}],
            host_rows={"a": {"pod_id": 3}})
        assert g["nodes"].shape[1] == 7
        assert g["nodes"][0][-1] == 3.0


class TestPodscopeTierMarks:
    def make_snaps(self):
        # two daemons in different pods; d2 pulled its piece from d1
        flight = {
            "peer_id": "d2-peer", "state": "success", "started_at": 0.0,
            "summary": {
                "task_id": "t1", "pieces": 1, "bytes_p2p": 100,
                "bytes_source": 0,
                "piece_rows": [{"piece": 0, "parent": "d1-peer",
                                "bytes": 100, "start_ms": 0.0,
                                "wire_ms": 1.0, "ttfb_ms": 0.1,
                                "queue_ms": 0.0, "hbm_ms": 0.0,
                                "total_ms": 1.1}],
            },
            "events": [],
        }
        serve_flight = {"peer_id": "d1-peer", "state": "serving",
                        "started_at": 0.0, "summary": {"task_id": "t1"},
                        "events": []}
        return [
            {"addr": "d1:1", "pod": "pod-0",
             "flights": {"t1": serve_flight}},
            {"addr": "d2:1", "pod": "pod-1", "flights": {"t1": flight}},
        ]

    def test_cross_pod_edge_marked_and_rendered(self):
        from dragonfly2_tpu.common import podscope
        report = podscope.aggregate(self.make_snaps())
        t = report["tasks"]["t1"]
        edge = next(e for e in t["edges"] if e["src"] == "d1:1")
        assert edge["cross_pod"] is True
        assert t["cross_pod_bytes"] == 100
        text = podscope.render_pod(report)
        assert "[dcn]" in text and "federation:" in text
        assert report["daemons_detail"]["d1:1"]["pod"] == "pod-0"

    def test_same_pod_edges_unmarked(self):
        from dragonfly2_tpu.common import podscope
        snaps = self.make_snaps()
        snaps[1]["pod"] = "pod-0"
        report = podscope.aggregate(snaps)
        t = report["tasks"]["t1"]
        assert all(not e.get("cross_pod") for e in t["edges"])
        assert t["cross_pod_bytes"] == 0
        assert "[dcn]" not in podscope.render_pod(report)


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
