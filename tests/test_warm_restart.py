"""Warm restart e2e: a seed is killed and restarted mid-swarm. The
restarted daemon must re-index its on-disk pieces (crc-verified), re-seed
its PEX digests within ONE gossip round, and serve the swarm WITHOUT
re-downloading a byte — the PR 4/5 seed-restart scenario made trivial by
the content-addressed store's crash-safe reload."""

import asyncio
import os

import pytest

# real daemons + full pulls + gossip rounds: seconds of wall time by
# design — tier-1 excludes it (ROADMAP -m 'not slow')
pytestmark = pytest.mark.slow

from test_daemon_e2e import daemon_config
from test_p2p import seed_daemon_with

from dragonfly2_tpu.daemon.config import SchedulerConfig as DaemonSchedCfg
from dragonfly2_tpu.daemon.daemon import Daemon
from dragonfly2_tpu.idl.messages import DownloadRequest


async def _await_holder(index, task_id: str, timeout_s: float = 5.0) -> None:
    deadline = asyncio.get_running_loop().time() + timeout_s
    while asyncio.get_running_loop().time() < deadline:
        if index.parents_for(task_id):
            return
        await asyncio.sleep(0.05)
    raise AssertionError(f"no swarm holder for {task_id[:12]} within "
                         f"{timeout_s}s")


def test_seed_restart_rejoins_as_holder_with_zero_redownload(tmp_path):
    """Kill + restart a seed mid-swarm: the restart must (a) reload its
    pieces from disk with zero origin traffic and zero re-downloads, (b)
    push its reloaded digests to the swarm in its FIRST gossip round (the
    boot-time initial round — no leecher action required), and (c) serve
    a fresh leecher the whole task byte-identical with the origin gone."""

    async def go():
        data = os.urandom((9 << 20) + 333)           # 3 pieces
        seed, origin, url, task_id, _peer = await seed_daemon_with(
            tmp_path, data)
        await origin.cleanup()      # from here, bytes exist ONLY on disk

        # the swarm: one live leecher that knows the seed via gossip
        leech_cfg = daemon_config(tmp_path, "leech")
        leech_cfg.scheduler = DaemonSchedCfg(addresses=[])   # pex-only pod
        leech_cfg.probe_enabled = False
        leech_cfg.pex.bootstrap = [f"127.0.0.1:{seed.upload_server.port}"]
        leech_cfg.pex.interval_s = 3600.0    # rounds driven explicitly
        leech = Daemon(leech_cfg)
        await leech.start()
        try:
            assert await leech.pex.round() == 1
            assert len(leech.pex.index.parents_for(task_id)) == 1

            # ---- kill the seed mid-swarm, restart over the same workdir
            seed_port = seed.upload_server.port
            await seed.stop()
            leech.pex.index.forget_host(next(iter(
                leech.pex.index._tasks[task_id])))   # swarm saw it die
            assert not leech.pex.index.parents_for(task_id)

            seed2_cfg = daemon_config(tmp_path, "seed")
            seed2_cfg.scheduler = DaemonSchedCfg(addresses=[])
            seed2_cfg.probe_enabled = False
            # the restarted seed knows only its bootstrap peer; its BOOT
            # round must push the reloaded digests there unprompted
            seed2_cfg.pex.bootstrap = [
                f"127.0.0.1:{leech.upload_server.port}"]
            seed2_cfg.pex.interval_s = 3600.0
            seed2 = Daemon(seed2_cfg)
            await seed2.start()
            try:
                # (a) reloaded, verified, NOT re-downloaded: the storage
                # holds the task as complete, yet no conductor ever ran
                # (and the origin is long gone, so a re-pull would fail)
                assert seed2.storage_mgr.reloaded_tasks >= 1
                ts = seed2.storage_mgr.find_completed_task(task_id)
                assert ts is not None and len(ts.md.pieces) == 3
                assert seed2.ptm.conductor(task_id) is None

                # (b) PEX holder within one gossip round — the initial
                # boot round already pushed; no leecher round needed
                await _await_holder(leech.pex.index, task_id)
                entry = leech.pex.index.parents_for(task_id)[0]
                assert entry.done
                assert entry.download_port == seed2.upload_server.port

                # (c) a fresh leecher joins the swarm and pulls the task
                # entirely from the restarted seed (origin is gone)
                l2_cfg = daemon_config(tmp_path, "leech2")
                l2_cfg.scheduler = DaemonSchedCfg(addresses=[])
                l2_cfg.probe_enabled = False
                l2_cfg.pex.bootstrap = [
                    f"127.0.0.1:{seed2.upload_server.port}"]
                l2_cfg.pex.interval_s = 3600.0
                leech2 = Daemon(l2_cfg)
                await leech2.start()
                try:
                    assert await leech2.pex.round() >= 1
                    out = tmp_path / "restart.bin"
                    async for _ in leech2.ptm.start_file_task(
                            DownloadRequest(url=url, output=str(out),
                                            disable_back_source=True,
                                            timeout_s=60.0)):
                        pass
                    assert out.read_bytes() == data
                    c = leech2.ptm.conductor(task_id)
                    assert c.state == c.SUCCESS
                    assert c.traffic_source == 0     # zero origin bytes
                    assert c.traffic_p2p == len(data)
                    # the seed served from its RELOADED storage: its serve
                    # journal has rows, its download journal has none
                    seed_flight = seed2.flight_recorder.get(task_id)
                    assert seed_flight is not None
                    assert seed_flight.state == "serving"
                    assert seed_flight.serves
                finally:
                    await leech2.stop()

                # the restarted seed's upload port may have moved — assert
                # the swarm learned the NEW address, not a stale ghost
                assert seed2.upload_server.port != 0
                assert seed_port != 0
            finally:
                await seed2.stop()
        finally:
            await leech.stop()

    asyncio.run(go())


def test_restart_with_torn_piece_refills_only_the_hole(tmp_path):
    """Crash-rot on one piece: the boot verify drops exactly that piece,
    the task demotes to partial, and the next pull re-fetches ONLY the
    hole from origin (the surviving pieces land as placements)."""

    async def go():
        data = os.urandom((9 << 20) + 333)           # 3 pieces
        seed, origin, url, task_id, _peer = await seed_daemon_with(
            tmp_path, data)
        ts = seed.storage_mgr.get(task_id)
        p1 = ts.md.pieces[1]
        await seed.stop()

        # rot piece 1 on disk while the daemon is down
        with open(ts.data_path(), "r+b") as f:
            f.seek(p1.start + 7)
            f.write(b"\xde\xad\xbe\xef")

        seed2 = Daemon(daemon_config(tmp_path, "seed"))
        await seed2.start()
        try:
            ts2 = seed2.storage_mgr.get(task_id)
            assert ts2 is not None
            assert sorted(ts2.md.pieces) == [0, 2]   # the hole, verified
            assert not ts2.md.done
            out = tmp_path / "refill.bin"
            async for _ in seed2.ptm.start_file_task(DownloadRequest(
                    url=url, output=str(out), timeout_s=60.0)):
                pass
            assert out.read_bytes() == data
            c = seed2.ptm.conductor(task_id)
            assert c.state == c.SUCCESS
            # only the rotted piece crossed the origin uplink
            assert c.traffic_source == p1.size
            assert c.traffic_placed == len(data) - p1.size
        finally:
            await seed2.stop()
            await origin.cleanup()

    asyncio.run(go())


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
