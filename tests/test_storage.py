"""Stage-2 tests: piece store write/read/verify, reload, GC, subtasks."""

import os

import pytest

from dragonfly2_tpu.common import digest as digestlib
from dragonfly2_tpu.common.errors import Code, DFError
from dragonfly2_tpu.common.piece import compute_piece_size, piece_count, piece_range
from dragonfly2_tpu.idl.messages import TaskType
from dragonfly2_tpu.storage.manager import StorageConfig, StorageManager
from dragonfly2_tpu.storage.metadata import TaskMetadata


def make_manager(tmp_path, **kw):
    return StorageManager(StorageConfig(data_dir=str(tmp_path / "data"), **kw))


def fill_task(mgr, task_id: str, content: bytes, task_type=TaskType.STANDARD):
    size = compute_piece_size(len(content))
    n = piece_count(len(content), size)
    ts = mgr.register_task(TaskMetadata(
        task_id=task_id, task_type=task_type, url=f"http://o/{task_id}",
        content_length=len(content), total_piece_count=n, piece_size=size))
    for i in range(n):
        off, ln = piece_range(i, size, len(content))
        ts.write_piece(i, off, content[off:off + ln])
    ts.mark_done(success=True, digest=digestlib.for_bytes("sha256", content))
    return ts


class TestTaskStorage:
    def test_write_read_roundtrip(self, tmp_path):
        mgr = make_manager(tmp_path)
        content = os.urandom(300_000)
        ts = fill_task(mgr, "a" * 64, content)
        assert ts.read_piece(0)[:16] == content[:16]
        got = b"".join(ts.read_piece(p.num) for p in ts.piece_infos())
        assert got == content
        assert ts.verify_content()

    def test_digest_mismatch_rejected(self, tmp_path):
        mgr = make_manager(tmp_path)
        ts = mgr.register_task(TaskMetadata(task_id="b" * 64))
        bad = "crc32c:" + "0" * 8
        with pytest.raises(DFError) as ei:
            ts.write_piece(0, 0, b"data", bad)
        assert ei.value.code == Code.CLIENT_DIGEST_MISMATCH
        assert ts.piece_infos() == []

    def test_duplicate_piece_idempotent(self, tmp_path):
        mgr = make_manager(tmp_path)
        ts = mgr.register_task(TaskMetadata(task_id="c" * 64))
        m1 = ts.write_piece(0, 0, b"xxxx")
        m2 = ts.write_piece(0, 0, b"yyyy")  # ignored
        assert m1 is m2
        assert ts.read_piece(0) == b"xxxx"

    def test_missing_piece(self, tmp_path):
        mgr = make_manager(tmp_path)
        ts = mgr.register_task(TaskMetadata(task_id="d" * 64))
        with pytest.raises(DFError) as ei:
            ts.read_piece(7)
        assert ei.value.code == Code.CLIENT_PIECE_NOT_FOUND

    def test_store_to_output(self, tmp_path):
        mgr = make_manager(tmp_path)
        content = os.urandom(50_000)
        ts = fill_task(mgr, "e" * 64, content)
        out = tmp_path / "out.bin"
        ts.store_to(str(out))
        assert out.read_bytes() == content
        # ranged store
        out2 = tmp_path / "out2.bin"
        ts.store_to(str(out2), range_start=100, range_length=500)
        assert out2.read_bytes() == content[100:600]


class TestReload:
    def test_completed_tasks_survive_restart(self, tmp_path):
        mgr = make_manager(tmp_path)
        content = os.urandom(100_000)
        fill_task(mgr, "f" * 64, content)
        # partial task: registered but never done
        mgr.register_task(TaskMetadata(task_id="9" * 64)).persist()

        mgr2 = make_manager(tmp_path)
        ts = mgr2.find_completed_task("f" * 64)
        assert ts is not None
        got = b"".join(ts.read_piece(p.num) for p in ts.piece_infos())
        assert got == content
        # partial was discarded as invalid
        assert mgr2.get("9" * 64) is None

    def test_find_partial_completed(self, tmp_path):
        mgr = make_manager(tmp_path)
        fill_task(mgr, "a1" + "0" * 62, os.urandom(10_000))
        assert mgr.find_partial_completed_task("a1" + "0" * 62, 0, 5000) is not None
        assert mgr.find_partial_completed_task("a1" + "0" * 62, 9000, 5000) is None
        assert mgr.find_partial_completed_task("nope", 0, 10) is None


class TestGC:
    def test_ttl_eviction_spares_persistent(self, tmp_path):
        mgr = make_manager(tmp_path, task_ttl_s=0.0)
        fill_task(mgr, "1" * 64, b"x" * 1000)
        fill_task(mgr, "2" * 64, b"y" * 1000, task_type=TaskType.PERSISTENT)
        import time
        time.sleep(0.01)
        n = mgr.try_gc()
        assert n == 1
        assert mgr.get("1" * 64) is None
        assert mgr.get("2" * 64) is not None

    def test_capacity_eviction_oldest_first(self, tmp_path):
        mgr = make_manager(tmp_path, capacity_bytes=10_000,
                           disk_gc_high_ratio=0.5, disk_gc_low_ratio=0.3)
        ts_old = fill_task(mgr, "3" * 64, b"a" * 4000)
        ts_old.md.access_time -= 100
        fill_task(mgr, "4" * 64, b"b" * 4000)
        n = mgr.try_gc()  # 8000/10000 > 0.5 high: evict to <=3000
        assert n >= 1
        assert mgr.get("3" * 64) is None  # oldest went first


class TestSubtask:
    def test_subtask_shares_parent_file(self, tmp_path):
        mgr = make_manager(tmp_path)
        parent_id = "p" * 64
        sub = mgr.register_subtask(TaskMetadata(
            task_id="s" * 64, parent_task_id=parent_id,
            range_start=1000, range_length=2000, content_length=2000))
        sub.write_piece(0, 0, b"A" * 1500)
        sub.write_piece(1, 1500, b"B" * 500)
        sub.mark_done(success=True)
        assert sub.read_piece(0) == b"A" * 1500
        # bytes physically live at parent's offset
        parent = mgr.get(parent_id)
        assert parent.read_range(1000, 4) == b"AAAA"
        assert parent.read_range(2500, 4) == b"BBBB"
        out = tmp_path / "sub.bin"
        sub.store_to(str(out))
        assert out.read_bytes() == b"A" * 1500 + b"B" * 500


class TestNative:
    def test_native_crc32c_matches_python(self):
        from dragonfly2_tpu.common.digest import _crc32c_py
        from dragonfly2_tpu.storage import native
        if not native.available():
            pytest.skip("native lib not built")
        data = os.urandom(100_000)
        assert native.hash_bytes("crc32c", data) == f"{_crc32c_py(data):08x}"

    def test_native_sha_md5_match_hashlib(self):
        import hashlib
        from dragonfly2_tpu.storage import native
        if not native.available():
            pytest.skip("native lib not built")
        data = os.urandom(64 * 1024 + 17)
        assert native.hash_bytes("sha256", data) == hashlib.sha256(data).hexdigest()
        assert native.hash_bytes("md5", data) == hashlib.md5(data).hexdigest()


class TestNativePieceIO:
    """native/dfnative.cc piece IO (VERDICT carried item: the bindings'
    'aligned file piece IO' claim must match the exports)."""

    def test_piece_write_read_roundtrip(self, tmp_path):
        from dragonfly2_tpu.storage import native
        if not native.available():
            pytest.skip("native lib not built")
        path = str(tmp_path / "f.bin")
        open(path, "wb").write(b"\0" * 256)
        data = os.urandom(100)
        crc = native.piece_write(path, 50, data)
        assert crc is not None and len(crc) == 8
        # fused crc matches the standalone hash
        from dragonfly2_tpu.common import digest as digestlib
        assert digestlib.hash_bytes("crc32c", data) == crc
        assert native.piece_read(path, 50, 100) == data
        # short read past EOF returns what exists
        assert len(native.piece_read(path, 200, 100)) == 56

    def test_piece_write_missing_file_raises(self, tmp_path):
        from dragonfly2_tpu.storage import native
        if not native.available():
            pytest.skip("native lib not built")
        with pytest.raises(OSError):
            native.piece_write(str(tmp_path / "nope.bin"), 0, b"x")

    def test_store_fused_path_detects_corruption(self, tmp_path):
        """A wrong crc32c digest is caught by the fused write pass and the
        piece is NOT recorded (the region stays absent)."""
        from dragonfly2_tpu.storage import native
        if not native.available():
            pytest.skip("native lib not built")
        from dragonfly2_tpu.common.errors import DFError
        from dragonfly2_tpu.storage.metadata import TaskMetadata
        from dragonfly2_tpu.storage.store import TaskStorage
        md = TaskMetadata(task_id="t" * 64, url="u", content_length=200,
                          total_piece_count=2, piece_size=100)
        ts = TaskStorage(str(tmp_path), md)
        with pytest.raises(DFError):
            ts.write_piece(0, 0, b"a" * 100,
                           piece_digest="crc32c:00000000")
        assert 0 not in ts.md.pieces
        assert not ts.has_range(0, 100)
