"""TLS/plaintext mux rollout e2e: one port, both protocols, no flag day.

VERDICT r04 missing #2 / next #6. Reference ``pkg/rpc/mux.go`` accepts
TLS and h2c on the same listener; ``pkg/rpc/credential.go`` adds the
default/prefer/force rollout policies. The test upgrades a live plaintext
fleet to TLS with no dropped RPCs: a plaintext client keeps its existing
connection working across the policy flip to force, while new plaintext
connections are refused and TLS clients connect throughout.
"""

import asyncio
import os

import pytest

# cert minting rides the cryptography API — wheel or openssl-CLI shim
from dragonfly2_tpu.common import cryptoshim

if not cryptoshim.install():
    pytest.skip("no cryptography wheel and no openssl binary",
                allow_module_level=True)

from dragonfly2_tpu.common.certs import CertIssuer
from dragonfly2_tpu.idl.messages import Empty
from dragonfly2_tpu.rpc.client import Channel, ServiceClient
from dragonfly2_tpu.rpc.server import RPCServer, ServiceDef, TLSOptions


def _material(tmp_path, name: str = "srv"):
    """(cert_path, key_path, ca_path): a fresh 127.0.0.1 leaf named
    ``name`` from the issuer rooted at tmp_path (same CA per tmp_path)."""
    issuer = CertIssuer(str(tmp_path / "ca"))
    cert_pem, key_pem, _exp = issuer._mint("127.0.0.1")
    cert_p, key_p = tmp_path / f"{name}.crt", tmp_path / f"{name}.key"
    cert_p.write_bytes(cert_pem)
    key_p.write_bytes(key_pem)
    return str(cert_p), str(key_p), issuer.ca_cert_path


async def _server(tmp_path, policy: str) -> tuple[RPCServer, str]:
    cert, key, ca = _material(tmp_path)

    async def ping(req, ctx):
        return Empty()

    svc = ServiceDef("df.test.Ping")
    svc.unary_unary("Ping", ping)
    srv = RPCServer("127.0.0.1:0", tls=TLSOptions(cert, key),
                    tls_policy=policy)
    srv.register(svc)
    await srv.start()
    return srv, ca


class TestMuxRollout:
    def test_plaintext_fleet_upgrades_to_tls_with_no_dropped_rpcs(
            self, tmp_path):
        async def main():
            srv, ca_path = await _server(tmp_path, "default")
            addr = f"127.0.0.1:{srv.port}"
            try:
                # live plaintext fleet member: connection established now
                plain_a = Channel(addr)
                ping_a = ServiceClient(plain_a, "df.test.Ping")
                assert isinstance(await ping_a.unary(
                    "Ping", Empty(), timeout=10), Empty)

                # TLS client on the SAME port, simultaneously
                tls_c = Channel(addr, tls_ca=ca_path)
                ping_c = ServiceClient(tls_c, "df.test.Ping")
                assert isinstance(await ping_c.unary(
                    "Ping", Empty(), timeout=10), Empty)

                # rollout complete: retire plaintext, runtime flip
                srv.mux.policy = "force"

                # the live plaintext member's ESTABLISHED connection keeps
                # serving — no dropped RPCs at the flip
                for _ in range(3):
                    assert isinstance(await ping_a.unary(
                        "Ping", Empty(), timeout=10), Empty)

                # ...but NEW plaintext connections are refused. A new
                # fleet member is a new process: grpc's global subchannel
                # pool would otherwise silently ride plain_a's pre-flip
                # TCP connection, so give this channel its own pool.
                plain_b = Channel(addr, options=[
                    ("grpc.use_local_subchannel_pool", 1)])
                ping_b = ServiceClient(plain_b, "df.test.Ping")
                with pytest.raises(Exception):
                    await ping_b.unary("Ping", Empty(), timeout=3)
                await plain_b.close()

                # TLS connects fine under force
                tls_d = Channel(addr, tls_ca=ca_path)
                ping_d = ServiceClient(tls_d, "df.test.Ping")
                assert isinstance(await ping_d.unary(
                    "Ping", Empty(), timeout=10), Empty)

                await asyncio.gather(plain_a.close(), tls_c.close(),
                                     tls_d.close())
            finally:
                await srv.stop()

        asyncio.run(main())

    def test_prefer_policy_serves_both(self, tmp_path):
        async def main():
            srv, ca_path = await _server(tmp_path, "prefer")
            addr = f"127.0.0.1:{srv.port}"
            try:
                for ch in (Channel(addr), Channel(addr, tls_ca=ca_path)):
                    client = ServiceClient(ch, "df.test.Ping")
                    assert isinstance(await client.unary(
                        "Ping", Empty(), timeout=10), Empty)
                    await ch.close()
            finally:
                await srv.stop()

        asyncio.run(main())

    def test_force_policy_skips_mux_entirely(self, tmp_path):
        """force at construction = plain TLS port, no front listener."""
        async def main():
            srv, ca_path = await _server(tmp_path, "force")
            try:
                assert srv.mux is None
                ch = Channel(f"127.0.0.1:{srv.port}", tls_ca=ca_path)
                client = ServiceClient(ch, "df.test.Ping")
                assert isinstance(await client.unary(
                    "Ping", Empty(), timeout=10), Empty)
                await ch.close()
            finally:
                await srv.stop()

        asyncio.run(main())

    def test_mux_with_port_range_spec(self, tmp_path):
        """A port-RANGE address composes with the mux: the front binds the
        first free port in the range (pre-bound socket handoff)."""
        async def main():
            from test_launchers import free_port

            cert, key, ca = _material(tmp_path)
            base = free_port()

            async def ping(req, ctx):
                return Empty()

            svc = ServiceDef("df.test.Ping")
            svc.unary_unary("Ping", ping)
            srv = RPCServer(f"127.0.0.1:{base}-{base + 10}",
                            tls=TLSOptions(cert, key), tls_policy="default")
            srv.register(svc)
            await srv.start()
            try:
                assert base <= srv.port <= base + 10
                for ch in (Channel(f"127.0.0.1:{srv.port}"),
                           Channel(f"127.0.0.1:{srv.port}", tls_ca=ca)):
                    out = await ServiceClient(ch, "df.test.Ping").unary(
                        "Ping", Empty(), timeout=10)
                    assert isinstance(out, Empty)
                    await ch.close()
            finally:
                await srv.stop()

        asyncio.run(main())

    def test_upload_data_plane_muxes_plain_and_mtls(self, tmp_path):
        """The PIECE plane rolls out the same way (our data plane is
        HTTPS, not gRPC, so the reference's mux story must cover it too):
        one upload port serves plaintext HTTP and mTLS HTTPS during
        rollout; force-flip refuses new plaintext while mTLS (client cert
        REQUIRED) keeps serving."""
        async def main():
            import aiohttp

            from dragonfly2_tpu.storage.manager import (StorageConfig,
                                                        StorageManager)
            from dragonfly2_tpu.storage.metadata import TaskMetadata
            from dragonfly2_tpu.daemon.upload_server import UploadServer

            # one stored piece to serve
            mgr = StorageManager(StorageConfig(data_dir=str(tmp_path / "s"),
                                               task_ttl_s=3600))
            payload = os.urandom(256 * 1024)
            md = TaskMetadata(task_id="a" * 64, url="http://o/x",
                              content_length=len(payload),
                              total_piece_count=1, piece_size=len(payload))
            ts = mgr.register_task(md)
            ts.write_piece(0, 0, payload)
            ts.mark_done(success=True)

            cert, key, ca = _material(tmp_path)
            # DISTINCT client leaf from the SAME issuer (the server
            # REQUIRES a fleet-CA-signed client cert: mTLS is mutual)
            ccert, ckey, _ = _material(tmp_path, name="client")
            srv = UploadServer(mgr, host="127.0.0.1")
            srv.tls = (cert, key, ca)
            srv.tls_policy = "default"
            await srv.start()
            try:
                url_path = f"/download/{'a' * 3}/{'a' * 64}"
                rng_hdr = {"Range": f"bytes=0-{len(payload) - 1}"}
                plain_url = f"http://127.0.0.1:{srv.port}{url_path}"
                tls_url = f"https://127.0.0.1:{srv.port}{url_path}"

                async with aiohttp.ClientSession() as s:
                    async with s.get(plain_url, params={"peerId": "p1"},
                                     headers=rng_hdr) as resp:
                        assert resp.status == 206
                        assert await resp.read() == payload

                import ssl as _ssl
                ctx = _ssl.create_default_context(cafile=ca)
                ctx.check_hostname = False
                ctx.load_cert_chain(ccert, ckey)
                async with aiohttp.ClientSession(
                        connector=aiohttp.TCPConnector(ssl=ctx)) as s:
                    async with s.get(tls_url, params={"peerId": "p2"},
                                     headers=rng_hdr) as resp:
                        assert resp.status == 206
                        assert await resp.read() == payload

                srv.mux.policy = "force"
                async with aiohttp.ClientSession(
                        connector=aiohttp.TCPConnector(
                            force_close=True)) as s:
                    with pytest.raises(Exception):
                        async with s.get(plain_url, params={"peerId": "p3"},
                                         headers=rng_hdr,
                                         timeout=aiohttp.ClientTimeout(
                                             total=5)) as resp:
                            await resp.read()
                async with aiohttp.ClientSession(
                        connector=aiohttp.TCPConnector(ssl=ctx)) as s:
                    async with s.get(tls_url, params={"peerId": "p4"},
                                     headers=rng_hdr) as resp:
                        assert resp.status == 206
            finally:
                await srv.stop()

        asyncio.run(main())

    def test_unknown_policy_rejected(self, tmp_path):
        async def main():
            with pytest.raises(ValueError):
                await _server(tmp_path, "sometimes")

        asyncio.run(main())


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
