"""TLS/plaintext mux rollout e2e: one port, both protocols, no flag day.

VERDICT r04 missing #2 / next #6. Reference ``pkg/rpc/mux.go`` accepts
TLS and h2c on the same listener; ``pkg/rpc/credential.go`` adds the
default/prefer/force rollout policies. The test upgrades a live plaintext
fleet to TLS with no dropped RPCs: a plaintext client keeps its existing
connection working across the policy flip to force, while new plaintext
connections are refused and TLS clients connect throughout.
"""

import asyncio

import pytest

from dragonfly2_tpu.common.certs import CertIssuer
from dragonfly2_tpu.idl.messages import Empty
from dragonfly2_tpu.rpc.client import Channel, ServiceClient
from dragonfly2_tpu.rpc.server import RPCServer, ServiceDef, TLSOptions


def _material(tmp_path):
    """(cert_path, key_path, ca_path) for a 127.0.0.1 server leaf."""
    issuer = CertIssuer(str(tmp_path / "ca"))
    cert_pem, key_pem, _exp = issuer._mint("127.0.0.1")
    cert_p, key_p = tmp_path / "srv.crt", tmp_path / "srv.key"
    cert_p.write_bytes(cert_pem)
    key_p.write_bytes(key_pem)
    return str(cert_p), str(key_p), issuer.ca_cert_path


async def _server(tmp_path, policy: str) -> tuple[RPCServer, str]:
    cert, key, ca = _material(tmp_path)

    async def ping(req, ctx):
        return Empty()

    svc = ServiceDef("df.test.Ping")
    svc.unary_unary("Ping", ping)
    srv = RPCServer("127.0.0.1:0", tls=TLSOptions(cert, key),
                    tls_policy=policy)
    srv.register(svc)
    await srv.start()
    return srv, ca


class TestMuxRollout:
    def test_plaintext_fleet_upgrades_to_tls_with_no_dropped_rpcs(
            self, tmp_path):
        async def main():
            srv, ca_path = await _server(tmp_path, "default")
            addr = f"127.0.0.1:{srv.port}"
            try:
                # live plaintext fleet member: connection established now
                plain_a = Channel(addr)
                ping_a = ServiceClient(plain_a, "df.test.Ping")
                assert isinstance(await ping_a.unary(
                    "Ping", Empty(), timeout=10), Empty)

                # TLS client on the SAME port, simultaneously
                tls_c = Channel(addr, tls_ca=ca_path)
                ping_c = ServiceClient(tls_c, "df.test.Ping")
                assert isinstance(await ping_c.unary(
                    "Ping", Empty(), timeout=10), Empty)

                # rollout complete: retire plaintext, runtime flip
                srv.mux.policy = "force"

                # the live plaintext member's ESTABLISHED connection keeps
                # serving — no dropped RPCs at the flip
                for _ in range(3):
                    assert isinstance(await ping_a.unary(
                        "Ping", Empty(), timeout=10), Empty)

                # ...but NEW plaintext connections are refused. A new
                # fleet member is a new process: grpc's global subchannel
                # pool would otherwise silently ride plain_a's pre-flip
                # TCP connection, so give this channel its own pool.
                plain_b = Channel(addr, options=[
                    ("grpc.use_local_subchannel_pool", 1)])
                ping_b = ServiceClient(plain_b, "df.test.Ping")
                with pytest.raises(Exception):
                    await ping_b.unary("Ping", Empty(), timeout=3)
                await plain_b.close()

                # TLS connects fine under force
                tls_d = Channel(addr, tls_ca=ca_path)
                ping_d = ServiceClient(tls_d, "df.test.Ping")
                assert isinstance(await ping_d.unary(
                    "Ping", Empty(), timeout=10), Empty)

                await asyncio.gather(plain_a.close(), tls_c.close(),
                                     tls_d.close())
            finally:
                await srv.stop()

        asyncio.run(main())

    def test_prefer_policy_serves_both(self, tmp_path):
        async def main():
            srv, ca_path = await _server(tmp_path, "prefer")
            addr = f"127.0.0.1:{srv.port}"
            try:
                for ch in (Channel(addr), Channel(addr, tls_ca=ca_path)):
                    client = ServiceClient(ch, "df.test.Ping")
                    assert isinstance(await client.unary(
                        "Ping", Empty(), timeout=10), Empty)
                    await ch.close()
            finally:
                await srv.stop()

        asyncio.run(main())

    def test_force_policy_skips_mux_entirely(self, tmp_path):
        """force at construction = plain TLS port, no front listener."""
        async def main():
            srv, ca_path = await _server(tmp_path, "force")
            try:
                assert srv.mux is None
                ch = Channel(f"127.0.0.1:{srv.port}", tls_ca=ca_path)
                client = ServiceClient(ch, "df.test.Ping")
                assert isinstance(await client.unary(
                    "Ping", Empty(), timeout=10), Empty)
                await ch.close()
            finally:
                await srv.stop()

        asyncio.run(main())

    def test_mux_with_port_range_spec(self, tmp_path):
        """A port-RANGE address composes with the mux: the front binds the
        first free port in the range (pre-bound socket handoff)."""
        async def main():
            from test_launchers import free_port

            cert, key, ca = _material(tmp_path)
            base = free_port()

            async def ping(req, ctx):
                return Empty()

            svc = ServiceDef("df.test.Ping")
            svc.unary_unary("Ping", ping)
            srv = RPCServer(f"127.0.0.1:{base}-{base + 10}",
                            tls=TLSOptions(cert, key), tls_policy="default")
            srv.register(svc)
            await srv.start()
            try:
                assert base <= srv.port <= base + 10
                for ch in (Channel(f"127.0.0.1:{srv.port}"),
                           Channel(f"127.0.0.1:{srv.port}", tls_ca=ca)):
                    out = await ServiceClient(ch, "df.test.Ping").unary(
                        "Ping", Empty(), timeout=10)
                    assert isinstance(out, Empty)
                    await ch.close()
            finally:
                await srv.stop()

        asyncio.run(main())

    def test_unknown_policy_rejected(self, tmp_path):
        async def main():
            with pytest.raises(ValueError):
                await _server(tmp_path, "sometimes")

        asyncio.run(main())


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
