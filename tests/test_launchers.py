"""Service launchers: the whole stack deployable from CLIs only.

VERDICT missing #6 / next #9 (reference ``cmd/`` launchers). Real OS
processes started via ``python -m dragonfly2_tpu.tools.{manager,scheduler,
trainer,daemon}``, discovery through the manager (scheduler registers +
adopts the seed-peer set; leecher discovers the scheduler), then a dfget
CLI pull that must ride the mesh end to end.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def spawn(mod: str, *args: str) -> subprocess.Popen:
    env = {**os.environ, "PYTHONPATH": REPO, "PYTHONUNBUFFERED": "1",
           "JAX_PLATFORMS": "cpu"}
    return subprocess.Popen(
        [PY, "-m", f"dragonfly2_tpu.tools.{mod}", *args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        cwd=REPO)


def wait_line(proc: subprocess.Popen, needle: str, timeout: float = 150.0) -> str:
    # Generous deadline: a co-tenant-loaded 1-vCPU host stretches
    # interpreter boot to tens of seconds, and a transient timeout here
    # reds the whole suite under the driver's -x gate. The deadline must
    # hold even when the service wedges with its pipe open — but NOT via
    # select()-before-readline(): the stdout is a BUFFERED text stream,
    # so a boot burst drains many lines into Python's buffer, the OS pipe
    # goes empty, and select never fires again while the wanted line sits
    # in the buffer (this exact bug hung the fakepod e2e). A reader
    # thread doing blocking readlines into a queue is buffering-immune;
    # it is reused across wait_line calls on the same process and dies
    # with it.
    import queue as _queue
    import threading

    q = getattr(proc, "_wl_queue", None)
    if q is None:
        q = _queue.Queue()
        proc._wl_queue = q

        def _pump() -> None:
            for ln in proc.stdout:
                q.put(ln)
            q.put(None)          # EOF sentinel

        threading.Thread(target=_pump, daemon=True).start()
    deadline = time.monotonic() + timeout
    lines: list[str] = []
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(
                f"{needle!r} not seen; got: {''.join(lines)[-2000:]}")
        try:
            line = q.get(timeout=min(remaining, 0.5))
        except _queue.Empty:
            continue
        if line is None:
            raise RuntimeError(f"process died: {''.join(lines)[-2000:]}")
        lines.append(line)
        if needle in line:
            return line


def test_full_stack_from_clis(tmp_path):
    blob = os.urandom(5 << 20)
    (tmp_path / "www").mkdir()
    (tmp_path / "www" / "blob.bin").write_bytes(blob)

    procs: list[subprocess.Popen] = []
    try:
        # origin
        origin_port = free_port()
        procs.append(subprocess.Popen(
            [PY, "-m", "http.server", str(origin_port), "--bind",
             "127.0.0.1"], cwd=str(tmp_path / "www"),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        url = f"http://127.0.0.1:{origin_port}/blob.bin"

        # manager
        grpc_port, rest_port = free_port(), free_port()
        mgr = spawn("manager", "--grpc-port", str(grpc_port),
                    "--rest-port", str(rest_port),
                    "--workdir", str(tmp_path / "mgr"),
                    "--db", str(tmp_path / "mgr" / "m.db"))
        procs.append(mgr)
        wait_line(mgr, "manager up:")
        mgr_addr = f"127.0.0.1:{grpc_port}"

        # seed daemon registers itself with the manager
        seed_rpc, seed_up = free_port(), free_port()
        seed_cfg = tmp_path / "seed.json"
        seed_cfg.write_text(json.dumps({
            "workdir": str(tmp_path / "seed"), "host_ip": "127.0.0.1",
            "hostname": "seed-cli", "is_seed": True,
            "rpc_port": seed_rpc,
            "manager_addresses": [mgr_addr],
            "upload": {"port": seed_up},
            "storage": {"gc_interval_s": 3600}}))
        seed = spawn("daemon", "--config", str(seed_cfg))
        procs.append(seed)
        wait_line(seed, "daemon up:")

        # scheduler discovers the seed THROUGH the manager
        sched_port = free_port()
        sched = spawn("scheduler", "--port", str(sched_port),
                      "--advertise-ip", "127.0.0.1",
                      "--manager", mgr_addr)
        procs.append(sched)
        wait_line(sched, "scheduler up:")
        sched_addr = f"127.0.0.1:{sched_port}"

        # trainer attaches to the manager too
        trainer = spawn("trainer", "--manager", mgr_addr,
                        "--data-dir", str(tmp_path / "tr"))
        procs.append(trainer)
        wait_line(trainer, "trainer up:")

        # manager REST sees both registered instances
        with urllib.request.urlopen(
                f"http://127.0.0.1:{rest_port}/api/v1/schedulers") as r:
            scheds = json.loads(r.read())
        assert any(s["port"] == sched_port for s in scheds)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{rest_port}/api/v1/seed-peers") as r:
            seeds = json.loads(r.read())
        assert any(s["port"] == seed_rpc for s in seeds)

        # leecher daemon + dfget CLI: bytes must ride the mesh
        sock = str(tmp_path / "leech.sock")
        leech_cfg = tmp_path / "leech.json"
        leech_cfg.write_text(json.dumps({
            "workdir": str(tmp_path / "leech"), "host_ip": "127.0.0.1",
            "hostname": "leech-cli", "unix_sock": sock,
            "scheduler": {"addresses": [sched_addr]},
            "storage": {"gc_interval_s": 3600}}))
        leech = spawn("daemon", "--config", str(leech_cfg))
        procs.append(leech)
        wait_line(leech, "daemon up:")

        out = tmp_path / "out.bin"
        rc = subprocess.run(
            [PY, "-m", "dragonfly2_tpu.tools.dfget", url, "-O", str(out),
             "--daemon-sock", sock, "--quiet"],
            env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"},
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert rc.returncode == 0, rc.stderr[-2000:]
        assert out.read_bytes() == blob
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_debug_endpoints_on_every_service(tmp_path):
    """pprof analogs fleet-wide (closes the last partial component row,
    VERDICT r04 next #8): scheduler, manager, and trainer launchers serve
    /debug/{stacks,profile} + /metrics on --debug-port, like the daemon's
    upload server already does (reference cmd/dependency/dependency.go:95
    gives every service a net/pprof listener)."""
    procs = []
    try:
        for mod, extra in (
                ("manager", ["--db", str(tmp_path / "m.db"),
                             "--workdir", str(tmp_path / "mgr")]),
                ("scheduler", []),
                ("trainer", ["--data-dir", str(tmp_path / "records")])):
            p = spawn(mod, "--debug-port", "-1", *extra)
            procs.append(p)
            line = wait_line(p, "debug on :", timeout=150)
            port = int(line.rsplit(":", 1)[1])
            wait_line(p, f"{mod} up:", timeout=150)
            stacks = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/stacks", timeout=10).read()
            assert b"asyncio tasks" in stacks, mod
            metrics = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10).read()
            assert metrics is not None, mod
            prof = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/profile?seconds=0.2",
                timeout=10).read()
            assert b"cumulative" in prof, mod
            if mod == "scheduler":
                # the pod-wide observability view rides the same port
                cluster = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/cluster",
                    timeout=10).read())
                assert cluster["hosts"] == {}
                assert "back_to_source_ratio" in cluster
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
