"""Podscope: pod-wide distribution-tree observability.

Units cover the serve-side edge journal (flight recorder + summarize),
the flight-ring visibility counters, the pure aggregation math
(tree/depth/edges/amplification/makespan/bottleneck), and the
``kind=edge`` record rows; the e2e drives ``dfdiag --pod`` against a real
3-daemon mesh (seed + 2 leechers, one of them P2P-served by the other
leecher) and asserts the rendered tree names every edge — with per-edge
bytes/bandwidth confirmed from BOTH ends — and the bottleneck.
"""

import asyncio
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))

from dragonfly2_tpu.common import podscope
from dragonfly2_tpu.daemon import flight_recorder as fr
from dragonfly2_tpu.daemon.flight_recorder import FlightRecorder, TaskFlight
from dragonfly2_tpu.tools import dfdiag

MB = 1 << 20


# ------------------------------------------------------------- serve rows

class TestServeJournal:
    def test_serve_rows_aggregate_into_uploads(self):
        f = TaskFlight("t" * 64, "me")
        f.serve(peer="child-a", addr="10.0.0.2", piece=0, nbytes=4 * MB,
                serve_ms=40.0, wait_ms=4.0)
        f.serve(peer="child-a", addr="10.0.0.2", piece=1, nbytes=4 * MB,
                serve_ms=40.0, wait_ms=0.0)
        f.serve(peer="child-b", addr="10.0.0.3", piece=0, nbytes=2 * MB,
                serve_ms=10.0, wait_ms=0.0)
        s = f.summarize()
        assert s["bytes_served"] == 10 * MB
        ups = s["uploads"]
        assert ups["child-a"]["pieces"] == 2
        assert ups["child-a"]["bytes"] == 8 * MB
        assert ups["child-a"]["wait_ms"] == 4.0
        # 8 MiB over 80 ms = 100 MiB/s
        assert ups["child-a"]["serve_bps"] == 100 * MB
        assert ups["child-b"]["addr"] == "10.0.0.3"
        # the timeline carries the raw rows for podscope stitching
        rows = f.timeline()["serves"]
        assert len(rows) == 3
        assert rows[0]["stage"] == fr.UPLOAD
        assert rows[0]["peer"] == "child-a"

    def test_serve_ring_bounded_separately_from_events(self):
        f = TaskFlight("t" * 64, "me", max_events=8, max_serves=4)
        for i in range(100):
            f.serve(peer="c", piece=i, nbytes=1)
        assert len(f.serves) == 4
        assert f.serves[-1][3] == 99          # newest kept
        assert len(f.events) == 0             # download journal untouched

    def test_summary_memo_sees_new_serves(self):
        f = TaskFlight("t" * 64, "me")
        assert f.summarize()["bytes_served"] == 0
        f.serve(peer="c", piece=0, nbytes=512, serve_ms=1.0)
        assert f.summarize()["bytes_served"] == 512

    def test_compact_summary_caps_uploads(self):
        f = TaskFlight("t" * 64, "me")
        for i in range(20):
            f.serve(peer=f"c{i:02d}", piece=0, nbytes=1024 * (i + 1))
        c = f.compact_summary(max_parents=8)
        assert len(c["uploads"]) == 8
        # heaviest kept
        assert "c19" in c["uploads"]


class TestRingVisibility:
    def test_eviction_counted_and_occupancy(self):
        rec = FlightRecorder(max_tasks=3)
        for i in range(5):
            rec.begin(f"task-{i}", "p")
        assert rec.evicted == 2
        assert len(rec.index()) == 3

    def test_serving_get_or_create(self):
        rec = FlightRecorder(max_tasks=4)
        dl = rec.begin("dl-task", "me")
        assert rec.serving("dl-task") is dl          # download flight reused
        srv = rec.serving("cold-task", "me")
        assert srv is not None and srv.state == "serving"
        assert rec.serving("cold-task") is srv
        off = FlightRecorder(enabled=False)
        assert off.serving("x") is None

    def test_serve_traffic_never_evicts_download_flights(self):
        """A seed holding more tasks than max_tasks must not let serve
        churn flush its own download journals out of the ring."""
        rec = FlightRecorder(max_tasks=2)
        rec.begin("dl-1", "me")
        rec.begin("dl-2", "me")
        # ring full of download flights: serve-only tasks are refused,
        # never admitted by evicting a download journal
        assert rec.serving("cold-1") is None
        assert rec.get("dl-1") is not None and rec.get("dl-2") is not None
        assert rec.evicted == 0
        # a serve-only flight IS evictable by another serve-only task
        rec2 = FlightRecorder(max_tasks=2)
        rec2.begin("dl", "me")
        assert rec2.serving("cold-a") is not None
        assert rec2.serving("cold-b") is not None
        assert rec2.get("cold-a") is None            # serving evicted
        assert rec2.get("dl") is not None            # download survived
        assert rec2.evicted == 1


# ------------------------------------------------------------- aggregation

def _rows(parent, *, n=3, wire=10.0, size=4 * MB):
    src = "origin" if parent == "" else "p2p"
    return [{"piece": i, "parent": parent, "source": src, "bytes": size,
             "start_ms": 5.0 * i, "total_ms": wire + 2.0, "queue_ms": 0.5,
             "ttfb_ms": 1.5, "wire_ms": wire, "hbm_ms": 0.0}
            for i in range(n)]


def _serves(child, *, n=3, serve=8.0, size=4 * MB):
    return [{"t_ms": 10.0 * i, "peer": child, "addr": "127.0.0.1",
             "piece": i, "bytes": size, "serve_ms": serve, "wait_ms": 0.5}
            for i in range(n)]


def _flight(peer, parent, started, *, wire=10.0, serves=None,
            state="success", rung="p2p"):
    p2p = 0 if parent == "" else 12 * MB
    return {"peer_id": peer, "started_at": started, "state": state,
            "serves": serves or [],
            "summary": {"piece_rows": _rows(parent, wire=wire),
                        "bytes_p2p": p2p, "bytes_source": 12 * MB - p2p,
                        "slo_breaches": {}, "served_rung": rung}}


def _chain_snapshots():
    """origin -> seed -> l1 -> l2, l1->l2 slow, one daemon dead."""
    tid = "T" * 64
    return [
        {"addr": "seed:1", "flights": {tid: _flight(
            "seed-peer", "", 100.0, serves=_serves("l1-peer"))}},
        {"addr": "l1:1", "flights": {tid: _flight(
            "l1-peer", "seed-peer", 100.1, serves=_serves("l2-peer"))}},
        {"addr": "l2:1", "flights": {tid: _flight(
            "l2-peer", "l1-peer", 100.2, wire=120.0)}},
        {"addr": "dead:1", "error": "connection refused"},
    ], tid


class TestAggregate:
    def test_tree_depth_edges_and_confirmation(self):
        snaps, tid = _chain_snapshots()
        t = podscope.aggregate(snaps)["tasks"][tid]
        assert t["depth"] == 3
        assert t["daemons"] == 3 and t["complete"] == 3
        edges = {(e["src"], e["dst"]): e for e in t["edges"]}
        assert set(edges) == {("origin", "seed:1"), ("seed:1", "l1:1"),
                              ("l1:1", "l2:1")}
        assert all(e["bytes"] == 12 * MB for e in edges.values())
        # serve-journal stitching: p2p edges confirmed from both ends,
        # with the parent-side serve/limiter timings attached
        assert edges[("seed:1", "l1:1")]["confirmed"]
        assert edges[("seed:1", "l1:1")]["serve_ms"] == pytest.approx(24.0)
        assert edges[("seed:1", "l1:1")]["wait_ms"] == pytest.approx(1.5)
        assert not edges[("origin", "seed:1")]["confirmed"]
        # tree: every node hangs off its heaviest source
        assert t["tree"] == {"seed:1": "origin", "l1:1": "seed:1",
                             "l2:1": "l1:1"}

    def test_amplification_exact_when_origin_observed(self):
        snaps, tid = _chain_snapshots()
        t = podscope.aggregate(snaps)["tasks"][tid]
        # the origin served the content exactly once (the seed's fetch)
        assert t["origin_bytes"] == 12 * MB
        assert t["amplification"] == 1.0
        assert t["amplification_note"] == ""

    def test_amplification_preseeded_note(self):
        tid = "S" * 64
        snaps = [{"addr": "l1:1", "flights": {tid: _flight(
            "l1-peer", "seed-peer", 100.0)}}]
        t = podscope.aggregate(snaps)["tasks"][tid]
        assert t["origin_bytes"] == 0
        assert t["amplification"] == 1.0
        assert t["amplification_note"] == "seeded before observation"
        # a serve-only root holder sits at depth 1, its child at 2
        assert t["depth"] == 2

    def test_fallback_stitch_for_restarted_seed(self):
        """A restarted seed serves content it never downloaded here: its
        flight has state 'serving', NO peer id, and only serve rows. The
        exact (src_peer, dst_peer) key can't match, so the fallback
        stitches by child + uniqueness, confirms the edge, and relabels
        its src from the unresolvable peer id to the seed's address."""
        tid = "R" * 64
        seed_flight = {"peer_id": "", "started_at": 99.0,
                       "state": "serving", "serves": _serves("l1-peer"),
                       "summary": {"piece_rows": [], "bytes_p2p": 0,
                                   "bytes_source": 0}}
        snaps = [
            {"addr": "seed:1", "flights": {tid: seed_flight}},
            {"addr": "l1:1", "flights": {tid: _flight(
                "l1-peer", "old-seed-peer-id", 100.0)}},
        ]
        t = podscope.aggregate(snaps)["tasks"][tid]
        (e,) = t["edges"]
        assert e["src"] == "seed:1"          # relabeled to the daemon
        assert e["dst"] == "l1:1"
        assert e["confirmed"]
        assert e["serve_ms"] == pytest.approx(24.0)
        assert t["tree"] == {"l1:1": "seed:1"}
        assert t["depth"] == 2

    def test_fallback_stitch_never_steals_for_origin_edges(self):
        """A child that pulled SOME pieces from origin and some from a
        restarted seed: the origin edge must stay an origin edge — the
        seed's anonymous serve rows belong to the mesh edge only."""
        tid = "O" * 64
        seed_flight = {"peer_id": "", "started_at": 99.0,
                       "state": "serving", "serves": _serves("l1-peer"),
                       "summary": {"piece_rows": [], "bytes_p2p": 0,
                                   "bytes_source": 0}}
        mixed_rows = _rows("", n=2) + [
            {**r, "piece": r["piece"] + 2}
            for r in _rows("old-seed-peer", n=2)]
        snaps = [
            {"addr": "seed:1", "flights": {tid: seed_flight}},
            {"addr": "l1:1", "flights": {tid: {
                "peer_id": "l1-peer", "started_at": 100.0,
                "state": "success", "serves": [],
                "summary": {"piece_rows": mixed_rows,
                            "bytes_p2p": 8 * MB, "bytes_source": 8 * MB,
                            "slo_breaches": {}, "served_rung": "p2p"}}}},
        ]
        t = podscope.aggregate(snaps)["tasks"][tid]
        edges = {(e["src"], e["dst"]): e for e in t["edges"]}
        assert set(edges) == {("origin", "l1:1"), ("seed:1", "l1:1")}
        assert not edges[("origin", "l1:1")]["confirmed"]
        assert edges[("seed:1", "l1:1")]["confirmed"]

    def test_render_is_linear_on_dense_cross_serve_mesh(self):
        """A pex swarm where every daemon serves every later joiner has
        combinatorially many DAG paths; the render walks the TREE (one
        line per node) and summarizes cross edges, or dfdiag --pod would
        flood the terminal at exactly the pod sizes it exists for."""
        tid = "D" * 64
        n = 24
        snaps = []
        for i in range(n):
            parents = [f"d{j}-peer" for j in range(i)] or [""]
            rows = []
            for k, par in enumerate(parents):
                rows.append({"piece": k, "parent": par,
                             "source": "origin" if par == "" else "p2p",
                             "bytes": 4 * MB, "start_ms": 1.0 * k,
                             "total_ms": 12.0, "queue_ms": 0.5,
                             "ttfb_ms": 1.5, "wire_ms": 10.0,
                             "hbm_ms": 0.0})
            snaps.append({"addr": f"d{i}:1", "flights": {tid: {
                "peer_id": f"d{i}-peer", "started_at": 100.0 + i,
                "state": "success", "serves": [],
                "summary": {"piece_rows": rows,
                            "bytes_p2p": sum(r["bytes"] for r in rows
                                             if r["parent"]),
                            "bytes_source": sum(r["bytes"] for r in rows
                                                if not r["parent"]),
                            "slo_breaches": {}, "served_rung": "p2p"}}}})
        rep = podscope.aggregate(snaps)
        assert len(rep["tasks"][tid]["edges"]) == n * (n - 1) // 2 + 1
        text = podscope.render_pod(rep)
        lines = text.splitlines()
        # one line per node + headers/cross-note/verdict, never per-path
        assert len(lines) < 3 * n
        assert "cross edge" in text
        # children truncated by the per-node cap are accounted for by
        # the "+N more" line, never misdiagnosed as cross-serve cycles
        assert "cycle" not in text
        assert "more" in text

    def test_makespan_first_request_to_last_complete(self):
        snaps, tid = _chain_snapshots()
        t = podscope.aggregate(snaps)["tasks"][tid]
        # first start 100.0s; last completion = l2 start (100.2s) + its
        # last piece end (start_ms 10 + total 122 = 132ms)
        assert t["makespan_ms"] == pytest.approx(332.0, abs=0.5)

    def test_bottleneck_straggler_breach_and_verdict(self):
        snaps, tid = _chain_snapshots()
        rep = podscope.aggregate(snaps)
        b = rep["tasks"][tid]["bottleneck"]
        assert (b["src"], b["dst"]) == ("l1:1", "l2:1")
        assert b["straggler"]
        assert any(x.startswith("bottleneck:") for x in rep["breaches"])
        assert any(x.startswith("unreachable: dead:1")
                   for x in rep["breaches"])
        assert "bottleneck edge l1:1 -> l2:1" in rep["verdict"]
        text = podscope.render_pod(rep)
        assert "<- bottleneck" in text
        assert "[confirmed]" in text
        assert "UNREACHABLE dead:1" in text
        # tree renders root-down with the seed uplink line
        assert "seed uplink:" in text

    def test_incomplete_daemon_is_a_breach(self):
        tid = "I" * 64
        snaps = [
            {"addr": "a:1", "flights": {tid: _flight("a-peer", "", 1.0)}},
            {"addr": "b:1", "flights": {tid: _flight(
                "b-peer", "a-peer", 1.1, state="running")}},
        ]
        rep = podscope.aggregate(snaps)
        assert rep["tasks"][tid]["complete"] == 1
        assert any(x.startswith("incomplete:") for x in rep["breaches"])

    def test_span_serve_rows_keep_parent_piece_tally(self):
        """A grouped span GET journals ONE serve row spanning N pieces;
        the parent-side tallies must still agree with the child's
        per-piece rows."""
        f = TaskFlight("t" * 64, "me")
        f.serve(peer="c", piece=0, nbytes=16 * MB, serve_ms=40.0,
                pieces=4)
        s = f.summarize()
        assert s["uploads"]["c"]["pieces"] == 4
        assert f.timeline()["serves"][0]["pieces"] == 4

    def test_stalled_daemon_health_is_surfaced_and_breaches(self):
        snaps, tid = _chain_snapshots()
        snaps[1]["health"] = {"status": "stalled",
                              "loop": {"max_lag_s": 2.5}}
        snaps[1]["pex"] = {"peers": [{"addr": "x"}]}
        rep = podscope.aggregate(snaps)
        d = rep["daemons_detail"]["l1:1"]
        assert d["health_status"] == "stalled"
        assert d["pex_peers"] == 1
        assert any(x.startswith("health: l1:1") for x in rep["breaches"])

    def test_partially_confirmed_seed_uplink_not_inflated(self):
        """A node with one confirmed and one unconfirmed edge: the
        serve-journal rate applies only to the bytes it covered."""
        tid = "U" * 64
        snaps = [
            {"addr": "seed:1", "flights": {tid: _flight(
                "seed-peer", "", 100.0,
                serves=_serves("l1-peer", serve=100.0))}},
            {"addr": "l1:1", "flights": {tid: _flight(
                "l1-peer", "seed-peer", 100.1)}},
            # l2 also pulled from the seed, but the seed's serve rows for
            # it are gone (ring evicted): unconfirmed edge
            {"addr": "l2:1", "flights": {tid: _flight(
                "l2-peer", "seed-peer", 100.2)}},
        ]
        t = podscope.aggregate(snaps)["tasks"][tid]
        su = t["seed_uplink"]
        assert su["node"] == "seed:1" and su["bytes"] == 24 * MB
        # 12 MiB confirmed over 300ms serve time = 40 MiB/s — NOT 24 MiB
        # over the same 300ms (80 MiB/s, the pre-fix inflation)
        assert su["est_bandwidth_bps"] == pytest.approx(40 * MB, rel=0.01)

    def test_healthy_pod_no_breaches(self):
        tid = "H" * 64
        snaps = [
            {"addr": "a:1", "flights": {tid: _flight(
                "a-peer", "", 1.0, serves=_serves("b-peer"))}},
            {"addr": "b:1", "flights": {tid: _flight(
                "b-peer", "a-peer", 1.1)}},
        ]
        rep = podscope.aggregate(snaps)
        assert rep["breaches"] == []
        # the slowest edge is still NAMED (informational), but nothing
        # rises to a breach — the CI gate stays green
        assert "BREACH" not in rep["verdict"]
        assert "bottleneck edge" in rep["verdict"]

    def test_bench_summary_shape(self):
        snaps, tid = _chain_snapshots()
        s = podscope.bench_summary(podscope.aggregate(snaps)["tasks"][tid])
        assert s["depth"] == 3 and s["amplification"] == 1.0
        assert s["edges"] == 3
        assert s["edge_bandwidth_bps"]["p5"] \
            <= s["edge_bandwidth_bps"]["p95"]
        assert s["seed_uplink"]["node"] == "seed:1"


class TestEdgeRecords:
    def test_on_flight_emits_edge_rows(self):
        from dragonfly2_tpu.idl.messages import Host
        from dragonfly2_tpu.scheduler.records import DownloadRecords
        from dragonfly2_tpu.scheduler.resource import Resource, Task
        res = Resource()
        task = Task("t" * 64, "u")
        host = res.store_host(Host(id="h-child", ip="127.0.0.1", port=1,
                                   download_port=2))
        peer = res.get_or_create_peer("child", task, host)
        rec = DownloadRecords()
        rec.on_flight(peer, {"per_parent": {
            "parentA": {"bytes": 8 * MB, "pieces": 2, "wire_ms": 80.0,
                        "throughput_bps": 100 * MB},
            "": {"bytes": 4 * MB, "pieces": 1, "wire_ms": 40.0,
                 "throughput_bps": 100 * MB}}})
        rows = rec.drain()
        edges = {r["src_peer_id"]: r for r in rows
                 if r["kind"] == "edge"}
        assert set(edges) == {"parentA", "origin"}
        assert edges["parentA"]["bandwidth_bps"] == 100 * MB
        assert edges["parentA"]["dst_peer_id"] == "child"
        assert edges["parentA"]["dst_host_id"] == "h-child"
        assert edges["origin"]["bytes"] == 4 * MB
        assert all(e["task_id"] == task.id and e["created_at"] > 0
                   for e in edges.values())
        # the flight row itself still rides along
        assert sum(1 for r in rows if r["kind"] == "flight") == 1


class TestServeJournalGating:
    def test_aborted_transmit_never_journals(self):
        """A child that disconnects mid-body releases the slot without
        the ok mark: the serve row must not claim the range landed."""
        from dragonfly2_tpu.daemon.upload_server import UploadServer, _Slot
        srv = UploadServer.__new__(UploadServer)
        srv._active = 0
        srv._active_cls = {}
        srv.bulk_limit = 1
        srv._bulk_waiters = []
        srv._transfer_ms = 0.0
        srv._transfer_ms_at = 0.0
        srv._slot_waiters = []
        fired = []
        slot = _Slot(srv)
        slot.on_release = lambda held: fired.append(slot.ok)
        slot.release()                      # aborted: ok never set
        ok_slot = _Slot(srv)
        ok_slot.on_release = lambda held: fired.append(ok_slot.ok)
        ok_slot.ok = True                   # transmit completed
        ok_slot.release()
        assert fired == [False, True]


class TestDfdiagPodCLI:
    def test_all_unreachable_is_io_exit_not_traceback(self, capsys):
        rc = dfdiag.main(["--pod", "127.0.0.1:9,127.0.0.1:19",
                          "--timeout", "2"])
        assert rc == dfdiag.EXIT_IO
        out = capsys.readouterr().out
        assert "UNREACHABLE 127.0.0.1:9" in out

    def test_flight_gate_exit_on_slo_breach(self, tmp_path, capsys):
        saved = tmp_path / "flight.json"
        saved.write_text(json.dumps({
            "summary": {"piece_rows": _rows("p"), "slo_breaches":
                        {"wire": 2}, "slo_budgets_ms": {"wire": 1.0}}}))
        rc = dfdiag.main(["--file", str(saved)])
        assert rc == dfdiag.EXIT_BREACH
        assert "SLO breach" in capsys.readouterr().out
        healthy = tmp_path / "ok.json"
        healthy.write_text(json.dumps({
            "summary": {"piece_rows": _rows("p"), "slo_breaches": {}}}))
        assert dfdiag.main(["--file", str(healthy)]) == dfdiag.EXIT_OK


# ------------------------------------------------------------------- e2e

class TestPodscopeE2E:
    def test_dfdiag_pod_over_three_daemon_mesh(self, tmp_path, capsys):
        """Acceptance: seed + 2 leechers (leech2 served P2P by leech1);
        ``dfdiag --pod`` renders the distribution tree with per-edge
        bytes/bandwidth — every p2p edge confirmed from both ends by the
        serve journal — and names the bottleneck edge."""
        from test_daemon_e2e import daemon_config
        from test_p2p import (ScriptedScheduler, ScriptedSession,
                              parent_addr, seed_daemon_with)

        from dragonfly2_tpu.daemon.daemon import Daemon
        from dragonfly2_tpu.idl.messages import (DownloadRequest,
                                                 PeerPacket, RegisterResult,
                                                 SizeScope)

        data = os.urandom(9 * MB + 333)          # 3 pieces
        checked = {}

        def scripted(daemon, peer_id):
            def make_session(conductor):
                return ScriptedSession(
                    RegisterResult(task_id=conductor.task_id,
                                   size_scope=SizeScope.NORMAL),
                    [PeerPacket(task_id=conductor.task_id,
                                src_peer_id=conductor.peer_id,
                                main_peer=parent_addr(daemon, peer_id))])
            return lambda d: ScriptedScheduler(make_session)

        async def pull(daemon, url, out):
            async for _ in daemon.ptm.start_file_task(DownloadRequest(
                    url=url, output=str(out), disable_back_source=True,
                    timeout_s=60.0)):
                pass

        async def go():
            seed, origin, url, task_id, seed_peer = await seed_daemon_with(
                tmp_path, data, name="pseed")
            l1 = Daemon(daemon_config(tmp_path, "pl1"),
                        scheduler_factory=scripted(seed, seed_peer))
            await l1.start()
            l2 = None
            try:
                await pull(l1, url, tmp_path / "l1.bin")
                l1_peer = l1.ptm.conductor(task_id).peer_id
                l2 = Daemon(daemon_config(tmp_path, "pl2"),
                            scheduler_factory=scripted(l1, l1_peer))
                await l2.start()
                await pull(l2, url, tmp_path / "l2.bin")
                assert (tmp_path / "l2.bin").read_bytes() == data

                addrs = [f"127.0.0.1:{d.upload_server.port}"
                         for d in (seed, l1, l2)]
                seed_addr, l1_addr, l2_addr = addrs
                # sync urllib must not run on the loop serving it
                snaps = await asyncio.to_thread(
                    podscope.collect_pod, addrs)
                report = podscope.aggregate(snaps)
                t = report["tasks"][task_id]
                edges = {(e["src"], e["dst"]): e for e in t["edges"]}
                assert set(edges) == {("origin", seed_addr),
                                      (seed_addr, l1_addr),
                                      (l1_addr, l2_addr)}
                for e in edges.values():
                    assert e["bytes"] == len(data)
                # both-ends confirmation via the serve journal, with
                # parent-side serve timings attached
                assert edges[(seed_addr, l1_addr)]["confirmed"]
                assert edges[(seed_addr, l1_addr)]["serve_ms"] > 0
                assert edges[(l1_addr, l2_addr)]["confirmed"]
                assert edges[(l1_addr, l2_addr)]["bandwidth_bps"] > 0
                assert t["depth"] == 3
                assert t["amplification"] == 1.0
                assert t["complete"] == 3
                assert t["makespan_ms"] > 0
                assert t["bottleneck"] is not None
                # ring visibility rode the flight index
                idx = snaps[0]["flight_index"]
                assert idx["occupancy"] >= 1
                assert idx["max_tasks"] == 64
                assert "evicted_total" in idx
                # the CLI against the live mesh
                rc = await asyncio.to_thread(
                    dfdiag.main, ["--pod", ",".join(addrs)])
                checked["rc"] = rc
                rc_json = await asyncio.to_thread(
                    dfdiag.main, ["--pod", ",".join(addrs), "--json"])
                checked["rc_json"] = rc_json
                checked["edges"] = edges
                checked["addrs"] = addrs
            finally:
                if l2 is not None:
                    await l2.stop()
                await l1.stop()
                await seed.stop()
                await origin.cleanup()

        asyncio.run(go())
        out = capsys.readouterr().out
        text, _, json_text = out.partition("{")
        # rendered tree: every daemon appears, edges carry bytes, the
        # bottleneck is named
        addrs = checked["addrs"]
        for addr in addrs:
            assert addr in text
        assert "origin" in text
        assert "9.0MiB/3pc" in text
        assert "[confirmed]" in text
        assert "<- bottleneck" in text
        assert "pod verdict:" in text
        # healthy mesh: exits 0, or 3 only for a named breach
        assert checked["rc"] in (dfdiag.EXIT_OK, dfdiag.EXIT_BREACH)
        report = json.loads("{" + json_text)
        assert set(report["tasks"]) and report["unreachable"] == {}
        assert checked["rc_json"] == (
            dfdiag.EXIT_BREACH if report["breaches"] else dfdiag.EXIT_OK)


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
