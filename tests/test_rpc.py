"""Stage-1 RPC tests: IDL codec round-trips, gRPC unary/stream calls with
DFError propagation, consistent-hash balancer."""

import asyncio

import pytest

from dragonfly2_tpu.common.errors import Code, DFError
from dragonfly2_tpu.idl import dumps, loads
from dragonfly2_tpu.idl.messages import (
    DownloadRequest, Host, HostType, PeerAddr, PeerPacket, PieceInfo,
    PiecePacket, Priority, RegisterPeerTaskRequest, SizeScope, TopologyInfo,
    UrlMeta,
)
from dragonfly2_tpu.rpc import Channel, ConsistentHashPool, HashRing, RPCServer, ServiceClient, ServiceDef


class TestCodec:
    def test_roundtrip_nested(self):
        req = RegisterPeerTaskRequest(
            url="http://origin/f.bin",
            url_meta=UrlMeta(digest="sha256:aa", tag="t", priority=Priority.LEVEL2),
            peer_id="p1",
            peer_host=Host(id="h1", ip="10.0.0.1", port=65000,
                           type=HostType.SUPER_SEED,
                           topology=TopologyInfo(slice_name="v5p-8", worker_index=2,
                                                 ici_coords=(0, 1, 2), num_chips=4)),
        )
        out = loads(dumps(req))
        assert isinstance(out, RegisterPeerTaskRequest)
        assert out.url_meta.priority is Priority.LEVEL2
        assert out.peer_host.type is HostType.SUPER_SEED
        # bare-tuple-annotated fields round-trip as tuples, so messages compare equal
        assert out.peer_host.topology.ici_coords == (0, 1, 2)
        assert out == req

    def test_bytes_and_lists(self):
        pkt = PiecePacket(task_id="t", piece_infos=[
            PieceInfo(piece_num=i, range_start=i * 4, range_size=4, digest=f"crc32c:{i:08x}")
            for i in range(3)
        ], total_piece_count=3)
        out = loads(dumps(pkt))
        assert [p.piece_num for p in out.piece_infos] == [0, 1, 2]

    def test_unknown_fields_dropped(self):
        import msgpack
        raw = msgpack.packb({"__t": "UrlMeta", "tag": "x", "brand_new_field": 9})
        out = loads(raw)
        assert isinstance(out, UrlMeta) and out.tag == "x"

    def test_enum_coercion(self):
        pkt = PeerPacket(task_id="t", main_peer=PeerAddr(peer_id="p"), code=0)
        out = loads(dumps(pkt))
        assert out.main_peer.peer_id == "p"


class _EchoService:
    async def echo(self, request, context):
        return request

    async def fail(self, request, context):
        raise DFError(Code.SCHED_NEED_BACK_SOURCE, "fetch it yourself")

    async def countdown(self, request, context):
        for i in range(3):
            yield DownloadRequest(url=f"step-{i}")

    async def summarize(self, request_iter, context):
        n = 0
        async for _ in request_iter:
            n += 1
        return DownloadRequest(url=f"got-{n}")


async def _with_server(fn):
    svc = _EchoService()
    sdef = ServiceDef("df.test.Echo")
    sdef.unary_unary("Echo", svc.echo)
    sdef.unary_unary("Fail", svc.fail)
    sdef.unary_stream("Countdown", svc.countdown)
    sdef.stream_unary("Summarize", svc.summarize)
    server = RPCServer("127.0.0.1:0")
    server.register(sdef)
    await server.start()
    ch = Channel(f"127.0.0.1:{server.port}")
    client = ServiceClient(ch, "df.test.Echo")
    try:
        return await fn(client)
    finally:
        await ch.close()
        await server.stop(0)


class TestGRPC:
    def test_unary_roundtrip(self):
        async def go(client):
            out = await client.unary("Echo", DownloadRequest(url="http://x", rate_limit_bps=5))
            assert out.url == "http://x" and out.rate_limit_bps == 5
        asyncio.run(_with_server(go))

    def test_dferror_crosses_wire(self):
        async def go(client):
            with pytest.raises(DFError) as ei:
                await client.unary("Fail", DownloadRequest())
            assert ei.value.code == Code.SCHED_NEED_BACK_SOURCE
            assert "fetch it yourself" in ei.value.message
        asyncio.run(_with_server(go))

    def test_server_stream(self):
        async def go(client):
            urls = [m.url async for m in client.unary_stream("Countdown", DownloadRequest())]
            assert urls == ["step-0", "step-1", "step-2"]
        asyncio.run(_with_server(go))

    def test_client_stream(self):
        async def go(client):
            async def gen():
                for _ in range(5):
                    yield DownloadRequest()
            out = await client.stream_unary("Summarize", gen())
            assert out.url == "got-5"
        asyncio.run(_with_server(go))

    def test_health(self):
        async def go(client):
            health = ServiceClient(client.channel, "df.health.Health")
            from dragonfly2_tpu.idl.messages import Empty
            out = await health.unary("Check", Empty())
            assert isinstance(out, Empty)
        asyncio.run(_with_server(go))


class TestHashRing:
    def test_stable_assignment(self):
        ring = HashRing(["a:1", "b:1", "c:1"])
        picks = {k: ring.pick(k) for k in (f"task-{i}" for i in range(100))}
        # removing one node must not move keys between surviving nodes
        ring.remove("c:1")
        for k, before in picks.items():
            after = ring.pick(k)
            if before != "c:1":
                assert after == before

    def test_distribution_roughly_even(self):
        ring = HashRing([f"n{i}" for i in range(4)], replicas=128)
        counts = {}
        for i in range(4000):
            n = ring.pick(f"k{i}")
            counts[n] = counts.get(n, 0) + 1
        assert min(counts.values()) > 4000 / 4 * 0.5

    def test_pick_n_failover_order(self):
        ring = HashRing(["a", "b", "c"])
        order = ring.pick_n("task-x", 3)
        assert len(order) == 3 and order[0] == ring.pick("task-x")
        assert set(order) == {"a", "b", "c"}

    def test_pool_update(self):
        pool = ConsistentHashPool(["127.0.0.1:1", "127.0.0.1:2"])
        assert pool.addresses() == {"127.0.0.1:1", "127.0.0.1:2"}
        pool.update(["127.0.0.1:2", "127.0.0.1:3"])
        assert pool.addresses() == {"127.0.0.1:2", "127.0.0.1:3"}


class TestListeners:
    def test_port_range_listen(self):
        """reference pkg/rpc/server_listen.go ListenWithPortRange: the
        server binds the first free port in the configured range."""
        async def main():
            from test_launchers import free_port
            from dragonfly2_tpu.idl.messages import Empty
            from dragonfly2_tpu.rpc.client import Channel, ServiceClient
            from dragonfly2_tpu.rpc.server import RPCServer, ServiceDef

            base = free_port()
            # occupy the first port of the range so the server must move on
            import socket as _socket
            blocker = _socket.socket()
            blocker.bind(("127.0.0.1", base))
            blocker.listen(1)
            try:
                async def ping(req, ctx):
                    return Empty()

                svc = ServiceDef("df.test.Ping")
                svc.unary_unary("Ping", ping)
                srv = RPCServer(f"127.0.0.1:{base}-{base + 20}")
                srv.register(svc)
                await srv.start()
                try:
                    assert base < srv.port <= base + 20
                    ch = Channel(f"127.0.0.1:{srv.port}")
                    out = await ServiceClient(ch, "df.test.Ping").unary(
                        "Ping", Empty(), timeout=10)
                    assert isinstance(out, Empty)
                    await ch.close()
                finally:
                    await srv.stop()
            finally:
                blocker.close()

        asyncio.run(main())

    def test_vsock_helper_contract(self):
        """vsock listen helper binds AF_VSOCK or raises OSError (never a
        silent TCP fallback); parse_port_spec handles singles + ranges."""
        import pytest as _pytest

        from dragonfly2_tpu.rpc.listen import (bind_port_in_range,
                                               parse_port_spec,
                                               vsock_listener)

        assert parse_port_spec("8000") == (8000, 8000)
        assert parse_port_spec("8000-8010") == (8000, 8010)
        with _pytest.raises(ValueError):
            parse_port_spec("9-8")
        s = bind_port_in_range("127.0.0.1", 0, 0)
        assert s.getsockname()[1] > 0
        s.close()
        try:
            v = vsock_listener(1234)
            v.close()
        except OSError:
            pass   # sandbox kernels commonly lack /dev/vsock

    def test_scheduler_connector_adopts_refreshed_set(self):
        """Manager-driven scheduler replacement reaches the consistent-hash
        ring without a daemon restart (reference daemon dynconfig)."""
        async def main():
            from dragonfly2_tpu.daemon.scheduler_session import (
                SchedulerConnector)
            from dragonfly2_tpu.idl.messages import Host

            host = Host(id="h", ip="127.0.0.1", port=1, download_port=2)
            conn = SchedulerConnector(["10.0.0.1:80", "10.0.0.2:80"], host)
            picks_before = {conn._ring.pick(f"t{i}") for i in range(50)}
            assert picks_before == {"10.0.0.1:80", "10.0.0.2:80"}
            conn.update_addresses(["10.0.0.2:80", "10.0.0.3:80"])
            picks_after = {conn._ring.pick(f"t{i}") for i in range(50)}
            assert picks_after == {"10.0.0.2:80", "10.0.0.3:80"}
            assert set(conn.addresses) == {"10.0.0.2:80", "10.0.0.3:80"}
            conn.update_addresses(["10.0.0.2:80", "10.0.0.3:80"])  # no-op

        asyncio.run(main())
