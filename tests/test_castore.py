"""Content-addressed store (storage/castore.py): cross-task dedupe,
crash-safe warm restart, popularity-aware eviction, shared-disk
accounting — plus the daemon-level placement paths (conductor/engine
consult the store before a single wire byte moves)."""

import asyncio
import json
import os

import pytest

from dragonfly2_tpu.common import digest as digestlib
from dragonfly2_tpu.common.piece import (compute_piece_size, piece_count,
                                         piece_range)
from dragonfly2_tpu.idl.messages import TaskType
from dragonfly2_tpu.storage.castore import content_key
from dragonfly2_tpu.storage.manager import StorageConfig, StorageManager
from dragonfly2_tpu.storage.metadata import METADATA_FILE, TaskMetadata


def make_manager(tmp_path, **kw):
    return StorageManager(StorageConfig(data_dir=str(tmp_path / "data"), **kw))


def fill_task(mgr, task_id: str, content: bytes, *, url: str = "",
              digest: str = "", task_type=TaskType.STANDARD,
              pieces_only: int | None = None, piece_size: int = 0):
    """Land ``content`` (optionally just the first N pieces) with per-piece
    digests recorded — the shape every CAS feature keys on."""
    size = piece_size or compute_piece_size(len(content))
    n = piece_count(len(content), size)
    algo = digestlib.preferred_piece_algo()
    ts = mgr.register_task(TaskMetadata(
        task_id=task_id, task_type=task_type,
        url=url or f"http://o/{task_id[:8]}",
        content_length=len(content), total_piece_count=n, piece_size=size,
        digest=digest))
    for i in range(n if pieces_only is None else pieces_only):
        off, ln = piece_range(i, size, len(content))
        ts.write_piece(i, off, content[off:off + ln],
                       digestlib.for_bytes(algo, content[off:off + ln]))
    if pieces_only is None:
        ts.mark_done(success=True, digest=digest)
    else:
        ts.persist()
    return ts


class TestContentKey:
    def test_complete_task_keys_on_geometry_and_digests(self, tmp_path):
        mgr = make_manager(tmp_path)
        content = os.urandom(300_000)
        a = fill_task(mgr, "a" * 64, content)
        b = fill_task(mgr, "b" * 64, content)
        assert content_key(a.md) == content_key(b.md)
        other = fill_task(mgr, "c" * 64, os.urandom(300_000))
        assert content_key(other.md) != content_key(a.md)

    def test_incomplete_or_digestless_has_no_key(self, tmp_path):
        mgr = make_manager(tmp_path)
        partial = fill_task(mgr, "d" * 64, os.urandom(300_000),
                            pieces_only=1)
        assert content_key(partial.md) is None
        bare = mgr.register_task(TaskMetadata(task_id="e" * 64))
        assert content_key(bare.md) is None


class TestPieceIndex:
    def test_place_piece_copies_and_verifies(self, tmp_path):
        mgr = make_manager(tmp_path)
        content = os.urandom(300_000)
        src = fill_task(mgr, "a" * 64, content)
        meta0 = src.md.pieces[0]
        dst = mgr.register_task(TaskMetadata(
            task_id="b" * 64, content_length=len(content),
            total_piece_count=src.md.total_piece_count,
            piece_size=src.md.piece_size))
        assert mgr.castore.place_piece(dst, 0, 0, meta0.size, meta0.digest)
        assert dst.read_piece(0) == content[:meta0.size]
        assert dst.md.pieces[0].source == "cas"

    def test_place_refuses_corrupt_holder_and_drops_loc(self, tmp_path):
        """Bit-rot on the holder's disk must fail the placement (the
        copy re-verifies) and un-index the lying location."""
        mgr = make_manager(tmp_path)
        content = os.urandom(300_000)
        src = fill_task(mgr, "a" * 64, content)
        meta0 = src.md.pieces[0]
        with open(src.data_path(), "r+b") as f:   # rot piece 0 in place
            f.seek(3)
            f.write(b"\xff\xff\xff")
        dst = mgr.register_task(TaskMetadata(task_id="b" * 64))
        assert not mgr.castore.place_piece(dst, 0, 0, meta0.size,
                                           meta0.digest)
        assert mgr.castore.find_piece(meta0.digest, meta0.size) is None

    def test_drop_task_unindexes(self, tmp_path):
        mgr = make_manager(tmp_path)
        content = os.urandom(120_000)
        src = fill_task(mgr, "a" * 64, content)
        dg = src.md.pieces[0].digest
        assert mgr.castore.find_piece(dg, src.md.pieces[0].size)
        mgr.delete_task("a" * 64)
        assert mgr.castore.find_piece(dg, src.md.pieces[0].size) is None

    def test_dedupe_disabled_runs_task_keyed(self, tmp_path):
        mgr = make_manager(tmp_path, dedupe_enabled=False)
        assert mgr.castore is None
        content = os.urandom(120_000)
        a = fill_task(mgr, "a" * 64, content)
        b = fill_task(mgr, "b" * 64, content)
        assert a.inode() != b.inode()      # every copy pays its own disk


class TestContentDedupe:
    def test_identical_completed_tasks_share_one_inode(self, tmp_path):
        mgr = make_manager(tmp_path)
        content = os.urandom(300_000)
        a = fill_task(mgr, "a" * 64, content)
        b = fill_task(mgr, "b" * 64, content)
        assert a.inode() == b.inode()
        assert a.nlink() >= 2
        # both aliases still read their own task id
        assert b.read_piece(0) == content[:b.md.pieces[0].size]
        logical, physical = mgr.usage()
        assert logical == 2 * len(content) if len(content) == a.disk_usage() \
            else logical == 2 * a.disk_usage()
        assert physical == a.disk_usage()

    def test_canonical_eviction_promotes_next_holder(self, tmp_path):
        """Deleting the canonical alias must neither orphan the shared
        bytes nor make the NEXT alias pay for its own copy."""
        mgr = make_manager(tmp_path)
        content = os.urandom(300_000)
        a = fill_task(mgr, "a" * 64, content)
        b = fill_task(mgr, "b" * 64, content)
        mgr.delete_task("a" * 64)
        assert b.read_piece(0) == content[:b.md.pieces[0].size]
        c = fill_task(mgr, "c" * 64, content)
        assert c.inode() == b.inode()      # promoted holder absorbed it

    def test_adopt_content_by_digest(self, tmp_path):
        mgr = make_manager(tmp_path)
        content = os.urandom(300_000)
        dg = digestlib.for_bytes("sha256", content)
        src = fill_task(mgr, "a" * 64, content, digest=dg)
        ts = mgr.adopt_content(TaskMetadata(task_id="b" * 64, digest=dg))
        assert ts is not None and ts.md.done and ts.md.success
        assert ts.inode() == src.inode()
        assert len(ts.md.pieces) == len(src.md.pieces)
        got = b"".join(ts.read_piece(p.num) for p in ts.piece_infos())
        assert got == content
        # unknown digest: no hit
        assert mgr.adopt_content(TaskMetadata(
            task_id="c" * 64, digest="sha256:" + "0" * 64)) is None


class TestWarmReload:
    def test_partial_task_survives_restart_with_verified_pieces(
            self, tmp_path):
        mgr = make_manager(tmp_path)
        content = os.urandom(600_000)
        fill_task(mgr, "a" * 64, content, pieces_only=2,
                  piece_size=200_000)

        mgr2 = make_manager(tmp_path)
        ts = mgr2.get("a" * 64)
        assert ts is not None and not ts.md.done
        assert sorted(ts.md.pieces) == [0, 1]
        stats = mgr2.verify_reloaded()
        assert stats["pieces_ok"] == 2 and stats["pieces_dropped"] == 0
        # the reloaded pieces are CAS-indexed: a second task places them
        meta0 = ts.md.pieces[0]
        dst = mgr2.register_task(TaskMetadata(task_id="b" * 64))
        assert mgr2.castore.place_piece(dst, 0, 0, meta0.size, meta0.digest)

    def test_verify_drops_rotted_piece_and_demotes_task(self, tmp_path):
        mgr = make_manager(tmp_path)
        content = os.urandom(600_000)
        ts = fill_task(mgr, "a" * 64, content, piece_size=200_000)
        p1 = ts.md.pieces[1]
        with open(ts.data_path(), "r+b") as f:
            f.seek(p1.start + 5)
            f.write(b"\x00\x11\x22\x33")

        mgr2 = make_manager(tmp_path)
        stats = mgr2.verify_reloaded()
        assert stats["pieces_dropped"] == 1
        ts2 = mgr2.get("a" * 64)
        assert ts2 is not None
        assert 1 not in ts2.md.pieces          # the hole, not the task
        assert not ts2.md.done                 # demoted: re-pull the hole
        assert mgr2.find_completed_task("a" * 64) is None
        # the demotion persisted: a THIRD boot sees the same partial
        mgr3 = make_manager(tmp_path)
        assert not mgr3.get("a" * 64).md.done

    def test_all_rotten_task_dropped(self, tmp_path):
        mgr = make_manager(tmp_path)
        ts = fill_task(mgr, "a" * 64, os.urandom(100_000))
        with open(ts.data_path(), "r+b") as f:
            f.write(os.urandom(100_000))       # total rot

        mgr2 = make_manager(tmp_path)
        stats = mgr2.verify_reloaded()
        assert stats["tasks_dropped"] == 1
        assert mgr2.get("a" * 64) is None

    def test_digestless_partial_discarded(self, tmp_path):
        """A partial whose pieces carry no digests cannot be re-verified
        — reload must discard it (the pre-CAS policy)."""
        mgr = make_manager(tmp_path)
        ts = mgr.register_task(TaskMetadata(task_id="a" * 64))
        ts.write_piece(0, 0, b"x" * 1000)
        ts.md.pieces[0].digest = ""            # simulate legacy metadata
        ts.persist()
        mgr2 = make_manager(tmp_path)
        assert mgr2.get("a" * 64) is None


class TestCrashSafeMetadata:
    def test_save_leaves_no_tmp_and_replaces_atomically(self, tmp_path):
        mgr = make_manager(tmp_path)
        ts = fill_task(mgr, "a" * 64, os.urandom(50_000))
        files = os.listdir(ts.dir)
        assert METADATA_FILE in files
        assert not [f for f in files if f.endswith(".tmp")]

    def test_truncated_metadata_never_boots(self, tmp_path):
        """A torn metadata file (the crash this satellite exists for) is
        rejected at load and the task discarded at reload — never half-
        parsed into a task with a lying piece table."""
        mgr = make_manager(tmp_path)
        ts = fill_task(mgr, "a" * 64, os.urandom(50_000))
        mpath = os.path.join(ts.dir, METADATA_FILE)
        raw = open(mpath, "rb").read()
        with open(mpath, "wb") as f:
            f.write(raw[:len(raw) // 2])       # torn mid-write
        with pytest.raises((ValueError, KeyError)):
            TaskMetadata.load(ts.dir)
        mgr2 = make_manager(tmp_path)
        assert mgr2.get("a" * 64) is None
        assert not os.path.isdir(ts.dir)


class TestPopularityEviction:
    def test_hot_task_outlives_cold_at_capacity(self, tmp_path):
        mgr = make_manager(tmp_path, capacity_bytes=10_000,
                          disk_gc_high_ratio=0.5, disk_gc_low_ratio=0.45)
        cold = fill_task(mgr, "1" * 64, os.urandom(4000))
        hot = fill_task(mgr, "2" * 64, os.urandom(4000))
        # make the HOT one the older-accessed of the two: without the
        # popularity signal the old ordering would evict it first
        hot.md.access_time -= 1000
        for _ in range(5):
            mgr.castore.record_serve("2" * 64, 4000)
        assert mgr.try_gc() >= 1
        assert mgr.get("2" * 64) is not None   # popularity saved it
        assert mgr.get("1" * 64) is None

    def test_gc_reports_logical_vs_physical_for_shared_bytes(self, tmp_path):
        """Evicting one alias of hardlink-shared content frees logical
        bytes but ~0 physical — the accounting must say so, and the sweep
        must keep going until the PHYSICAL watermark is met."""
        mgr = make_manager(tmp_path, capacity_bytes=10_000,
                          disk_gc_high_ratio=0.5, disk_gc_low_ratio=0.45)
        content = os.urandom(6000)
        a = fill_task(mgr, "1" * 64, content)
        b = fill_task(mgr, "2" * 64, content)
        assert a.inode() == b.inode()          # shared: physical 6000
        logical, physical = mgr.usage()
        assert (logical, physical) == (12000, 6000)
        a.md.access_time -= 100
        reclaimed = mgr.try_gc()               # 6000/10000 > 0.5
        assert reclaimed >= 1
        stats = mgr.last_gc_stats
        assert stats["logical_bytes_freed"] >= 6000
        # at least one evicted alias shared its inode: physical < logical
        assert stats["physical_bytes_freed"] < stats["logical_bytes_freed"]

    def test_ttl_eviction_still_spares_persistent(self, tmp_path):
        mgr = make_manager(tmp_path, task_ttl_s=0.0)
        fill_task(mgr, "1" * 64, b"x" * 1000)
        fill_task(mgr, "2" * 64, b"y" * 1000,
                  task_type=TaskType.PERSISTENT)
        import time
        time.sleep(0.01)
        assert mgr.try_gc() == 1
        assert mgr.get("2" * 64) is not None


class TestDaemonPlacement:
    """The tentpole's daemon half: announced pieces whose digests are
    already held land as placements — never dispatched to the wire."""

    def test_alias_pull_adopts_whole_content(self, tmp_path):
        """Same bytes under two URLs (distinct task ids): the second pull
        must move ZERO bytes from anywhere — whole-content adoption."""
        from test_daemon_e2e import daemon_config, start_origin

        from dragonfly2_tpu.daemon.daemon import Daemon
        from dragonfly2_tpu.idl.messages import DownloadRequest, UrlMeta

        data = os.urandom(5 << 20)
        dg = "sha256:" + __import__("hashlib").sha256(data).hexdigest()

        async def go():
            origin, base = await start_origin({"m1.bin": data,
                                               "m2.bin": data})
            daemon = Daemon(daemon_config(tmp_path, "d1"))
            await daemon.start()
            try:
                tids = []
                for name in ("m1.bin", "m2.bin"):
                    async for resp in daemon.ptm.start_file_task(
                            DownloadRequest(
                                url=f"{base}/{name}",
                                output=str(tmp_path / ("out-" + name)),
                                url_meta=UrlMeta(digest=dg),
                                timeout_s=60.0)):
                        tid = resp.task_id or None
                    tids.append(tid)
                assert (tmp_path / "out-m2.bin").read_bytes() == data
                c1 = daemon.ptm.conductor(tids[0])
                c2 = daemon.ptm.conductor(tids[1])
                assert c1.traffic_source == len(data)
                # the alias pull: zero origin, zero p2p, all placed
                assert c2.traffic_source == 0
                assert c2.traffic_p2p == 0
                assert c2.traffic_placed == len(data)
                ts1 = daemon.storage_mgr.get(tids[0])
                ts2 = daemon.storage_mgr.get(tids[1])
                assert ts1.inode() == ts2.inode()   # shared on disk
                summary = daemon.flight_recorder.get(tids[1]).summarize()
                assert summary["bytes_placed"] == len(data)
                assert summary["bytes_source"] == 0
            finally:
                await daemon.stop()
                await origin.cleanup()

        asyncio.run(go())

    def test_ranged_request_never_adopts_whole_content(self, tmp_path):
        """A ranged request carrying a whole-file digest must NOT be
        short-circuited by whole-content adoption (content_range is still
        unresolved when the conductor starts): the client gets exactly
        its range, not the full file under the ranged task id."""
        from test_daemon_e2e import daemon_config, start_origin

        from dragonfly2_tpu.daemon.daemon import Daemon
        from dragonfly2_tpu.idl.messages import DownloadRequest, UrlMeta

        data = os.urandom(2 << 20)
        dg = "sha256:" + __import__("hashlib").sha256(data).hexdigest()

        async def go():
            origin, base = await start_origin({"m.bin": data})
            daemon = Daemon(daemon_config(tmp_path, "d1"))
            await daemon.start()
            try:
                # the full content is held complete under the digest
                async for _ in daemon.ptm.start_file_task(DownloadRequest(
                        url=f"{base}/m.bin", url_meta=UrlMeta(digest=dg),
                        timeout_s=60.0)):
                    pass
                out = tmp_path / "range.bin"
                async for _ in daemon.ptm.start_file_task(DownloadRequest(
                        url=f"{base}/m.bin", output=str(out),
                        url_meta=UrlMeta(digest=dg,
                                         range="bytes=100-299"),
                        timeout_s=60.0)):
                    pass
                assert out.read_bytes() == data[100:300]
            finally:
                await daemon.stop()
                await origin.cleanup()

        asyncio.run(go())

    def test_engine_places_announced_digests_instead_of_pulling(
            self, tmp_path):
        """P2P path: a leecher that already holds the announced digests
        under ANOTHER task id places them locally — the parent's upload
        port never serves a byte for the alias task."""
        from test_daemon_e2e import daemon_config
        from test_p2p import (ScriptedScheduler, ScriptedSession,
                              parent_addr, seed_daemon_with)

        from dragonfly2_tpu.daemon.daemon import Daemon
        from dragonfly2_tpu.idl.messages import (DownloadRequest, PeerPacket,
                                                 RegisterResult, SizeScope,
                                                 UrlMeta)

        data = os.urandom((9 << 20) + 333)     # 3 pieces
        dg = "sha256:" + __import__("hashlib").sha256(data).hexdigest()

        async def go():
            seed, origin, url, task_id, seed_peer = await seed_daemon_with(
                tmp_path, data)
            # the seed also completes the ALIAS task (adoption by digest:
            # instant, no transfer) so it can announce it to the leecher
            async for _ in seed.ptm.start_file_task(DownloadRequest(
                    url=url + "?alias=2", url_meta=UrlMeta(digest=dg),
                    timeout_s=60.0)):
                pass
            await origin.cleanup()

            cfg = daemon_config(tmp_path, "leech")
            leech = Daemon(cfg, scheduler_factory=lambda d: ScriptedScheduler(
                lambda conductor: ScriptedSession(
                    RegisterResult(task_id=conductor.task_id,
                                   size_scope=SizeScope.NORMAL,
                                   content_length=len(data)),
                    [PeerPacket(task_id=conductor.task_id,
                                main_peer=parent_addr(seed, seed_peer))])))
            await leech.start()
            try:
                # first pull rides the mesh for real
                async for _ in leech.ptm.start_file_task(DownloadRequest(
                        url=url, disable_back_source=True,
                        timeout_s=60.0)):
                    pass
                c1 = leech.ptm.conductor(task_id)
                assert c1.traffic_p2p == len(data)
                served_before = seed.flight_recorder.get(task_id)
                # alias pull (same url_meta as the seed's, so the task ids
                # agree): the seed announces the same piece digests — every
                # piece places from the leecher's own disk. The content-
                # digest adoption does NOT fire here (the first pull never
                # recorded a whole-content digest), so this exercises the
                # per-piece engine consult, not the whole-task shortcut.
                async for resp in leech.ptm.start_file_task(DownloadRequest(
                        url=url + "?alias=2", url_meta=UrlMeta(digest=dg),
                        disable_back_source=True, timeout_s=60.0)):
                    alias_tid = resp.task_id or None
                c2 = leech.ptm.conductor(alias_tid)
                assert c2.state == c2.SUCCESS
                assert c2.traffic_p2p == 0
                assert c2.traffic_placed == len(data)
                alias_flight = seed.flight_recorder.get(alias_tid)
                assert alias_flight is None or not alias_flight.serves
                assert served_before is not None   # task1 DID serve
            finally:
                await leech.stop()
                await seed.stop()

        asyncio.run(go())


class TestPlacedObservability:
    def test_summary_counts_placed_bytes_and_podscope_reads_warm(self):
        from dragonfly2_tpu.common import podscope
        from dragonfly2_tpu.daemon import flight_recorder as fr

        flight = fr.TaskFlight("t" * 64, "peer-1")
        flight.event(fr.PLACED, 0, "cas", 4096)
        flight.event(fr.PLACED, 1, "cas", 4096)
        flight.state = "success"
        s = flight.summarize()
        assert s["bytes_placed"] == 8192
        assert s["placed_pieces"] == 2
        assert s["bytes_source"] == 0

        snap = {"addr": "d1:1", "flights": {
            "t" * 64: {"peer_id": "peer-1", "state": "success",
                       "started_at": 0.0, "summary": s,
                       "events": [], "serves": []}}}
        report = podscope.aggregate([snap])
        task = report["tasks"]["t" * 64]
        assert task["placed_bytes"] == 8192
        assert task["amplification"] == 0.0
        assert task["amplification_note"].startswith("healthy-warm")
        # a placement-only flight IS download activity: the healthiest
        # pod must never read as incomplete (or shrink the makespan set)
        assert task["daemons"] == 1
        assert task["complete"] == 1
        assert not [b for b in report["breaches"]
                    if "amplification" in b]
        rendered = podscope.render_pod(report)
        assert "(warm)" in rendered


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
