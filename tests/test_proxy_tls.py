"""HTTPS interception e2e: TLS registry pulls ride the mesh, not a tunnel.

VERDICT r1-r3 missing #1. A fake TLS registry (self-signed via its own CA)
serves a blob; the daemon proxy, with hijack enabled, MITMs the CONNECT
using its auto-generated CA, routes the blob through the P2P task path, and
the client (trusting only the proxy CA) gets byte-identical content. The
SNI listener is driven with a raw TLS client handshaking a name that only
exists in the ClientHello. Reference: client/daemon/proxy/cert.go:37,
proxy.go:268, proxy_sni.go:32.
"""

import asyncio
import hashlib
import os
import ssl

import pytest

# MITM cert minting rides the cryptography API — wheel or CLI shim
from dragonfly2_tpu.common import cryptoshim

if not cryptoshim.install():
    pytest.skip("no cryptography wheel and no openssl binary",
                allow_module_level=True)

from dragonfly2_tpu.common.certs import CertIssuer, generate_ca
from dragonfly2_tpu.daemon.config import (DaemonConfig, DownloadConfig,
                                          ProxyConfig, StorageSection)
from dragonfly2_tpu.daemon.daemon import Daemon

BLOB = os.urandom(6 << 20)
DIGEST = hashlib.sha256(BLOB).hexdigest()


async def start_tls_registry(tmp_path):
    """Fake registry over TLS with its own CA; returns (port, ca_path, hits)."""
    from aiohttp import web

    issuer = CertIssuer(str(tmp_path / "upstream-ca"))
    ctx = issuer.server_context("127.0.0.1")
    hits = {"blob": 0, "bytes": 0}

    async def blob(request: web.Request) -> web.Response:
        hits["blob"] += 1
        if request.method == "GET" and "Range" not in request.headers:
            hits["bytes"] += len(BLOB)   # metadata probes don't count
        return web.Response(body=BLOB,
                            content_type="application/octet-stream")

    app = web.Application()
    app.router.add_get("/v2/repo/blobs/sha256:" + DIGEST, blob)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0, ssl_context=ctx)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, port, issuer.ca_cert_path, hits


def make_daemon(tmp_path, upstream_ca: str, *, sni: bool = False) -> Daemon:
    return Daemon(DaemonConfig(
        workdir=str(tmp_path / "daemon"), host_ip="127.0.0.1",
        hostname="proxyd",
        storage=StorageSection(gc_interval_s=3600),
        download=DownloadConfig(source_ca=upstream_ca),
        proxy=ProxyConfig(enabled=True, hijack=True,
                          sni_port=-1 if sni else 0)))


class TestHTTPSInterception:
    def test_connect_is_mitmed_and_rides_the_mesh(self, tmp_path):
        async def main():
            import aiohttp

            runner, up_port, up_ca, hits = await start_tls_registry(tmp_path)
            daemon = make_daemon(tmp_path, up_ca)
            await daemon.start()
            try:
                proxy_url = f"http://127.0.0.1:{daemon.proxy_server.port}"
                # the client trusts ONLY the proxy's CA — a blind tunnel
                # would surface the upstream's (untrusted) cert and fail
                client_ctx = ssl.create_default_context(
                    cafile=daemon.proxy_server.ca_cert_path)
                client_ctx.check_hostname = False   # leaf is for 127.0.0.1
                url = (f"https://127.0.0.1:{up_port}/v2/repo/blobs/"
                       f"sha256:{DIGEST}")
                async with aiohttp.ClientSession() as s:
                    async with s.get(url, proxy=proxy_url,
                                     ssl=client_ctx) as resp:
                        assert resp.status == 200
                        body = await resp.read()
                assert hashlib.sha256(body).hexdigest() == DIGEST
                assert hits["bytes"] == len(BLOB)   # exactly one body pull
                # the blob landed in the PIECE STORE (mesh path, not relay):
                # a second pull is served without touching the upstream
                async with aiohttp.ClientSession() as s:
                    async with s.get(url, proxy=proxy_url,
                                     ssl=client_ctx) as resp:
                        body2 = await resp.read()
                assert hashlib.sha256(body2).hexdigest() == DIGEST
                assert hits["bytes"] == len(BLOB), \
                    "second pull must come from the mesh"
            finally:
                await daemon.stop()
                await runner.cleanup()

        asyncio.run(main())

    def test_sni_listener_mints_for_client_hello_name(self, tmp_path):
        async def main():
            runner, up_port, up_ca, hits = await start_tls_registry(tmp_path)
            daemon = make_daemon(tmp_path, up_ca, sni=True)
            await daemon.start()
            try:
                sni_port = daemon.proxy_server.sni_port
                assert sni_port
                client_ctx = ssl.create_default_context(
                    cafile=daemon.proxy_server.ca_cert_path)
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", sni_port, ssl=client_ctx,
                    server_hostname="registry.test")
                cert = writer.get_extra_info("peercert")
                names = {v for t, v in cert.get("subjectAltName", ())}
                assert "registry.test" in names   # minted for the SNI name
                writer.write(
                    f"GET /v2/repo/blobs/sha256:{DIGEST} HTTP/1.1\r\n"
                    f"Host: 127.0.0.1:{up_port}\r\n"
                    f"Connection: close\r\n\r\n".encode())
                await writer.drain()
                raw = await reader.read()
                writer.close()
                head, _, body = raw.partition(b"\r\n\r\n")
                assert b"200" in head.split(b"\r\n")[0]
                # chunked or plain body: normalize by stripping framing
                if b"chunked" in head.lower():
                    out = bytearray()
                    rest = body
                    while rest:
                        size_line, _, rest = rest.partition(b"\r\n")
                        n = int(size_line, 16)
                        if n == 0:
                            break
                        out += rest[:n]
                        rest = rest[n + 2:]
                    body = bytes(out)
                assert hashlib.sha256(body[:len(BLOB)]).hexdigest() == DIGEST
            finally:
                await daemon.stop()
                await runner.cleanup()

        asyncio.run(main())


class TestCerts:
    def test_ca_and_leaf_chain_verify(self, tmp_path):
        issuer = CertIssuer(str(tmp_path))
        ctx = issuer.server_context("example.test")
        assert ctx is issuer.server_context("example.test")   # cached
        # leaf files are transient (deleted after load_cert_chain) so
        # client-controlled names can't grow the disk; verify the chain
        # from a fresh in-memory mint instead
        leaves = os.path.join(str(tmp_path), "leaves")
        assert not os.listdir(leaves), "leaf files must not persist"
        from cryptography import x509
        cert_pem, _key_pem, _exp = issuer._mint("example.test")
        leaf = x509.load_pem_x509_certificate(cert_pem)
        assert leaf.issuer == issuer.ca_cert.subject
        san = leaf.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value
        assert "example.test" in san.get_values_for_type(x509.DNSName)

    def test_concurrent_mint_no_race(self, tmp_path):
        """Parallel first connections for one host must never load
        mismatched cert/key pairs (single-flight under the lock)."""
        import concurrent.futures

        issuer = CertIssuer(str(tmp_path))
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            list(pool.map(lambda i: issuer.server_context("race.test"),
                          range(200)))

    def test_generate_ca_roundtrip(self, tmp_path):
        cert_pem, key_pem = generate_ca()
        p = tmp_path / "ca.crt"
        k = tmp_path / "ca.key"
        p.write_bytes(cert_pem)
        k.write_bytes(key_pem)
        issuer = CertIssuer(str(tmp_path), ca_cert_path=str(p),
                            ca_key_path=str(k))
        issuer.server_context("10.0.0.1")   # IP SAN path


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
