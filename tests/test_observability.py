"""Observability plane: flight recorder, dfdiag, cluster view, metric
hygiene, exposition strictness, and the end-to-end one-trace assertion
(scheduler decision -> peer piece fetch -> HBM landing).
"""

import asyncio
import json
import os

import pytest

from dragonfly2_tpu.common.metrics import Registry
from dragonfly2_tpu.daemon import flight_recorder as fr
from dragonfly2_tpu.daemon.flight_recorder import FlightRecorder, TaskFlight
from dragonfly2_tpu.tools.dfdiag import (render_cluster, render_waterfall,
                                         verdict)


def synthetic_flight(*, max_events: int = 4096) -> TaskFlight:
    """Deterministic flight: events injected straight into the ring so
    stage durations are exact. Piece 0: fast p2p; piece 1: slow wire from
    a straggler parent; piece 2: back-source."""
    f = TaskFlight("t" * 64, "peer-x", max_events=max_events)
    rows = [
        # (t_ms, stage, piece, parent, bytes, dur_ms)
        (0.0, fr.REGISTERED, -1, "", 0, 0.0),
        (1.0, fr.SCHEDULED, 0, "parentA", 0, 0.0),
        (2.0, fr.DISPATCHED, 0, "parentA", 0, 0.0),
        (5.0, fr.FIRST_BYTE, 0, "parentA", 0, 0.0),
        (15.0, fr.WIRE_DONE, 0, "parentA", 4 << 20, 13.0),
        (16.0, fr.HBM_DONE, 0, "", 4 << 20, 0.0),
        (1.0, fr.SCHEDULED, 1, "parentB", 0, 0.0),
        (3.0, fr.DISPATCHED, 1, "parentB", 0, 0.0),
        (10.0, fr.FIRST_BYTE, 1, "parentB", 0, 0.0),
        (210.0, fr.WIRE_DONE, 1, "parentB", 4 << 20, 207.0),
        (212.0, fr.HBM_DONE, 1, "", 4 << 20, 0.0),
        (260.0, fr.WIRE_DONE, 2, "", 2 << 20, 40.0),
        (261.0, fr.HBM_SHARD, 0, "", 0, 6.0),
    ]
    for row in rows:
        f.events.append(row)
    f.state = "success"
    return f


class TestFlightRecorder:
    def test_summary_attribution(self):
        s = synthetic_flight().summarize()
        assert s["pieces"] == 3
        assert s["bytes_p2p"] == 8 << 20
        assert s["bytes_source"] == 2 << 20
        rows = {r["piece"]: r for r in s["piece_rows"]}
        # piece 0: queue 1ms, ttfb 3ms, wire 10ms, hbm 1ms
        assert rows[0]["queue_ms"] == 1.0
        assert rows[0]["ttfb_ms"] == 3.0
        assert rows[0]["wire_ms"] == 10.0
        assert rows[0]["hbm_ms"] == 1.0
        # piece 1 is the slowest and its wire transfer dominates
        slow = s["slowest_piece"]
        assert slow["piece"] == 1
        assert slow["dominant_stage"] == "wire"
        assert slow["parent"] == "parentB"
        # back-source piece back-dated from its recorded duration
        assert rows[2]["wire_ms"] == 40.0
        assert rows[2]["source"] == "origin"
        assert s["back_to_source_ratio"] == pytest.approx(0.2)
        assert s["hbm_dma_ms"] == 6.0
        # per-parent throughput: parentB moved the same bytes far slower
        pp = s["per_parent"]
        assert pp["parentA"]["throughput_bps"] > \
            pp["parentB"]["throughput_bps"]

    def test_compact_summary_caps_parents(self):
        f = TaskFlight("t" * 64, "p")
        for i in range(20):
            f.events.append((float(i), fr.WIRE_DONE, i, f"par{i:02d}",
                             1024, 1.0))
        c = f.compact_summary(max_parents=8)
        assert len(c["per_parent"]) == 8
        assert "piece_rows" not in c

    def test_event_ring_bounded(self):
        f = TaskFlight("t" * 64, "p", max_events=16)
        for i in range(1000):
            f.event(fr.WIRE_DONE, i, "a", 1)
        assert len(f.events) == 16
        # oldest dropped, newest kept
        assert f.events[-1][2] == 999

    def test_recorder_task_ring_and_disable(self):
        rec = FlightRecorder(max_tasks=4)
        for i in range(10):
            rec.begin(f"task-{i}", "p")
        assert len(rec.index()) == 4
        assert rec.get("task-9") is not None
        assert rec.get("task-0") is None
        off = FlightRecorder(enabled=False)
        assert off.begin("t", "p") is None
        assert off.index() == []


class TestDfdiag:
    def test_waterfall_rows_and_legend(self):
        s = synthetic_flight().summarize()
        text = render_waterfall(s, width=40)
        lines = text.splitlines()
        # header + column row + one row per piece + legend
        assert len(lines) == 2 + 3 + 1
        assert "legend:" in lines[-1]
        # the slow piece's bar is mostly wire glyphs
        row1 = next(ln for ln in lines if ln.strip().startswith("1 "))
        assert row1.count("=") > row1.count("-")
        assert "211ms" in row1

    def test_verdict_names_dominant_stage_and_straggler(self):
        s = synthetic_flight().summarize()
        v = verdict(s)
        # wire dominates both overall and on the slowest piece
        assert "wire transfer" in v
        assert "slowest piece 1" in v
        assert "straggler" in v
        assert "p50/p90/p99" in v

    def test_verdict_empty(self):
        assert "nothing to attribute" in verdict({"piece_rows": []})


class TestClusterView:
    def _peer(self, res, task, peer_id, host_id):
        from dragonfly2_tpu.idl.messages import Host
        host = res.store_host(Host(id=host_id, ip="127.0.0.1", port=1,
                                   download_port=2))
        return res.get_or_create_peer(peer_id, task, host)

    def _result(self, task_id, src, dst, size=1 << 20, cost_ms=10,
                success=True):
        from dragonfly2_tpu.idl.messages import PieceInfo, PieceResult
        return PieceResult(task_id=task_id, src_peer_id=src, dst_peer_id=dst,
                           success=success,
                           piece_info=PieceInfo(piece_num=0, range_size=size,
                                                download_cost_ms=cost_ms))

    def test_aggregation_and_stragglers(self):
        from dragonfly2_tpu.scheduler.cluster_view import ClusterView
        from dragonfly2_tpu.scheduler.resource import Resource, Task
        res = Resource()
        task = Task("t" * 64, "u")
        child = self._peer(res, task, "child", "h-child")
        fast = self._peer(res, task, "fast", "h-fast")
        slow = self._peer(res, task, "slow", "h-slow")
        view = ClusterView()
        for _ in range(8):
            view.on_piece(child, self._result(task.id, "child", "fast",
                                              cost_ms=10))
            view.on_piece(child, self._result(task.id, "child", "slow",
                                              cost_ms=500))
        view.on_piece(child, self._result(task.id, "child", "",
                                          size=2 << 20, cost_ms=50))
        view.on_piece(child, self._result(task.id, "child", "fast",
                                          success=False))
        view.on_flight(child, {"task_id": task.id, "state": "success",
                               "pieces": 17, "bytes_p2p": 16 << 20,
                               "bytes_source": 2 << 20,
                               "back_to_source_ratio": 0.11,
                               "tail_ms": {"p50": 10}})
        snap = view.snapshot()
        assert snap["bytes_p2p"] == 16 << 20
        assert snap["bytes_source"] == 2 << 20
        assert snap["back_to_source_ratio"] == pytest.approx(2 / 18, abs=1e-3)
        assert snap["hosts"]["h-child"]["fails"] == 1
        assert snap["hosts"]["h-child"]["flights"] == 1
        assert snap["hosts"]["h-child"]["last_flight"]["pieces"] == 17
        assert snap["hosts"]["h-fast"]["pieces_served"] == 8
        stragglers = {s["host_id"] for s in snap["stragglers"]}
        assert stragglers == {"h-slow"}
        # render path stays in sync with the snapshot schema
        text = render_cluster(snap)
        assert "STRAGGLER h-slow" in text
        assert "back-to-source" in text

    def test_too_few_hosts_no_straggler_verdict(self):
        from dragonfly2_tpu.scheduler.cluster_view import ClusterView
        from dragonfly2_tpu.scheduler.resource import Resource, Task
        res = Resource()
        task = Task("t" * 64, "u")
        child = self._peer(res, task, "c", "hc")
        self._peer(res, task, "p", "hp")
        view = ClusterView()
        for _ in range(6):
            view.on_piece(child, self._result(task.id, "c", "p",
                                              cost_ms=900))
        assert view.stragglers() == []

    def test_snapshot_ttl_cache_and_staleness(self, monkeypatch):
        import time as _time

        from dragonfly2_tpu.scheduler.cluster_view import ClusterView
        from dragonfly2_tpu.scheduler.resource import Resource, Task
        clk = [100.0]
        monkeypatch.setattr(_time, "monotonic", lambda: clk[0])
        res = Resource()
        task = Task("t" * 64, "u")
        child = self._peer(res, task, "c", "hc")
        view = ClusterView(snapshot_ttl_s=1.0)
        view.on_piece(child, self._result(task.id, "c", ""))
        s1 = view.snapshot()
        assert s1["staleness_s"] == 0.0
        assert s1["snapshot_ttl_s"] == 1.0
        # a report landing inside the TTL is invisible until expiry, and
        # the payload admits how old the view is
        view.on_piece(child, self._result(task.id, "c", ""))
        clk[0] = 100.5
        s2 = view.snapshot()
        assert s2["hosts"]["hc"]["pieces_down"] == 1   # cached vintage
        assert s2["staleness_s"] == 0.5
        clk[0] = 101.6
        s3 = view.snapshot()
        assert s3["hosts"]["hc"]["pieces_down"] == 2   # rebuilt
        assert s3["staleness_s"] == 0.0


class TestExpositionStrictness:
    """Registry.expose() exposition-format guarantees."""

    def test_label_escaping(self):
        r = Registry()
        c = r.counter("df_esc_total", "escapes", ("path",))
        c.labels('a"b\\c\nd').inc()
        text = r.expose()
        assert 'path="a\\"b\\\\c\\nd"' in text

    def test_histogram_inf_bucket_and_consistency(self):
        r = Registry()
        h = r.histogram("df_lat_seconds", "lat", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        text = r.expose()
        # +Inf bucket equals _count; buckets are cumulative
        assert 'df_lat_seconds_bucket{le="0.1"} 1.0' in text
        assert 'df_lat_seconds_bucket{le="1.0"} 2.0' in text
        assert 'df_lat_seconds_bucket{le="+Inf"} 4.0' in text
        assert "df_lat_seconds_count 4.0" in text
        assert "df_lat_seconds_sum 55.55" in text

    def test_histogram_labeled_inf_consistency(self):
        r = Registry()
        h = r.histogram("df_l2_seconds", "lat", ("op",), buckets=(1.0,))
        h.labels("read").observe(0.5)
        h.labels("read").observe(9.0)
        text = r.expose()
        assert 'df_l2_seconds_bucket{op="read",le="+Inf"} 2.0' in text
        assert 'df_l2_seconds_count{op="read"} 2.0' in text

    def test_duplicate_registration_type_errors(self):
        r = Registry()
        r.counter("df_dup_total", "x")
        with pytest.raises(TypeError, match="already registered"):
            r.gauge("df_dup_total", "x")
        with pytest.raises(TypeError, match="re-registered with labels"):
            r.counter("df_dup_total", "x", ("kind",))
        # identical re-registration is the supported idempotent path
        assert r.counter("df_dup_total", "x") is not None


# The metric-catalogue and flight-vocabulary lints that lived here
# (PR 1 namespace lint, PR 3 catalogue lint) moved into dflint as DF006
# rules — one registry, one walker, one output format. The tier-1 gate
# is tests/test_dflint.py::TestTier1Gate; the rule catalogue is
# docs/ANALYSIS.md.


class TestShaperMetrics:
    def test_shaper_exports_rate_tasks_and_bytes(self):
        from dragonfly2_tpu.common.metrics import REGISTRY
        from dragonfly2_tpu.daemon.traffic_shaper import TrafficShaper

        tasks_g = REGISTRY.gauge("df_shaper_tasks", "x")
        rate_g = REGISTRY.gauge("df_shaper_rate_bps", "x")
        bytes_c = REGISTRY.counter("df_shaper_throttled_bytes_total", "x")
        retunes = REGISTRY.counter("df_shaper_retunes_total", "x")
        before_bytes = bytes_c.value()
        before_retunes = retunes.value()

        shaper = TrafficShaper(total_rate_bps=1 << 20, kind="sampling")
        shaper.register("t1")
        shaper.register("t2")
        assert tasks_g.value() == 2
        assert rate_g.value() == 1 << 20
        shaper.record("t1", 4096)
        shaper.record("t2", 1024)
        assert bytes_c.value() == before_bytes + 5120
        assert retunes.value() >= before_retunes + 2
        shaper.unregister("t1")
        shaper.unregister("t2")
        assert tasks_g.value() == 0

    def test_unlimited_shaper_counts_no_throttled_bytes(self):
        from dragonfly2_tpu.common.metrics import REGISTRY
        from dragonfly2_tpu.daemon.traffic_shaper import TrafficShaper

        bytes_c = REGISTRY.counter("df_shaper_throttled_bytes_total", "x")
        before = bytes_c.value()
        shaper = TrafficShaper(total_rate_bps=0)
        shaper.register("t")
        shaper.record("t", 9999)
        # pass-through mode: the byte is already counted by the transfer
        # path; double-counting it here would overstate shaping
        assert bytes_c.value() == before
        shaper.unregister("t")


class TestGCMetrics:
    def test_gc_run_records_timestamp_duration_and_reclaimed(self):
        from dragonfly2_tpu.common.gc import GC, GCTask
        from dragonfly2_tpu.common.metrics import REGISTRY

        last = REGISTRY.gauge("df_gc_last_run_timestamp_seconds", "x",
                              ("task",))
        reclaimed = REGISTRY.counter("df_gc_reclaimed_total", "x", ("task",))
        runs = REGISTRY.counter("df_gc_runs_total", "x", ("task", "result"))
        dur = REGISTRY.histogram("df_gc_run_duration_seconds", "x",
                                 ("task",))

        async def go():
            gc = GC()
            gc.add(GCTask("sweep-a", 3600.0, lambda: 3))

            async def failing():
                raise RuntimeError("disk gone")

            gc.add(GCTask("sweep-b", 3600.0, failing))
            t0 = __import__("time").time()
            assert await gc.run_one("sweep-a") == 3
            assert await gc.run_one("sweep-a") == 3
            with pytest.raises(RuntimeError):
                await gc.run_one("sweep-b")
            assert last.value("sweep-a") >= t0
            assert reclaimed.value("sweep-a") == 6
            assert runs.value("sweep-a", "ok") == 2
            assert runs.value("sweep-b", "error") == 1
            # duration histogram saw both ok sweeps
            _counts, _total, n = dur.snapshot("sweep-a")
            assert n == 2
            # a failed sweep must NOT advance the liveness timestamp
            assert last.value("sweep-b") == 0.0

        asyncio.run(go())


class TestFlightHTTP:
    def test_debug_flight_endpoint_on_upload_server(self, tmp_path):
        """A real multi-piece back-source download leaves a queryable
        flight with a summary on /debug/flight/<task_id>."""
        import sys
        sys.path.insert(0, os.path.dirname(__file__))
        from test_daemon_e2e import daemon_config, start_origin

        from dragonfly2_tpu.daemon.daemon import Daemon
        from dragonfly2_tpu.idl.messages import DownloadRequest

        async def go():
            data = os.urandom((10 << 20) + 777)     # 3 pieces
            origin, base = await start_origin({"f.bin": data})
            daemon = Daemon(daemon_config(tmp_path, "flt"))
            await daemon.start()
            try:
                async for _ in daemon.ptm.start_file_task(DownloadRequest(
                        url=f"{base}/f.bin", output=str(tmp_path / "o"),
                        timeout_s=60.0)):
                    pass
                task_id = next(iter(daemon.ptm._conductors))
                import aiohttp
                port = daemon.upload_server.port
                async with aiohttp.ClientSession() as s:
                    async with s.get(f"http://127.0.0.1:{port}"
                                     f"/debug/flight") as r:
                        idx = await r.json()
                        assert idx["enabled"]
                        assert any(t["task_id"] == task_id
                                   for t in idx["tasks"])
                    # a task-id prefix resolves like a full id
                    async with s.get(f"http://127.0.0.1:{port}"
                                     f"/debug/flight/{task_id[:16]}") as r:
                        assert r.status == 200
                        flight = await r.json()
                    async with s.get(f"http://127.0.0.1:{port}"
                                     f"/debug/flight/nope-nope") as r:
                        assert r.status == 404
                assert flight["state"] == "success"
                summary = flight["summary"]
                assert summary["pieces"] == 3
                assert summary["bytes_source"] == len(data)
                assert summary["back_to_source_ratio"] == 1.0
                text = render_waterfall(summary)
                assert len([ln for ln in text.splitlines()
                            if "ms" in ln and "|" in ln]) >= 3
                assert "origin" in verdict(summary)
            finally:
                await daemon.stop()
                await origin.cleanup()

        asyncio.run(go())

    def test_disabled_recorder_records_nothing(self, tmp_path):
        import sys
        sys.path.insert(0, os.path.dirname(__file__))
        from test_daemon_e2e import daemon_config, start_origin

        from dragonfly2_tpu.daemon.daemon import Daemon
        from dragonfly2_tpu.idl.messages import DownloadRequest

        async def go():
            data = os.urandom(300_000)
            origin, base = await start_origin({"x.bin": data})
            cfg = daemon_config(tmp_path, "noflt")
            cfg.flight.enabled = False
            daemon = Daemon(cfg)
            await daemon.start()
            try:
                async for _ in daemon.ptm.start_file_task(DownloadRequest(
                        url=f"{base}/x.bin", output=str(tmp_path / "o"),
                        timeout_s=60.0)):
                    pass
                # no journal object on the conductor: the hot path never
                # paid for a single event
                conductor = next(iter(daemon.ptm._conductors.values()))
                assert conductor.flight is None
                assert daemon.flight_recorder.index() == []
            finally:
                await daemon.stop()
                await origin.cleanup()

        asyncio.run(go())


class TestOneTraceEndToEnd:
    def test_trace_spans_sched_decision_fetch_and_hbm(self, tmp_path):
        """ONE trace id covers the scheduler's register decision (joined
        over gRPC metadata), the piece fetches (joined over the piece
        HTTP header), and the HBM landing; and the flight summary rode
        the terminal PeerResult into the scheduler's cluster view."""
        import sys
        sys.path.insert(0, os.path.dirname(__file__))
        from test_daemon_e2e import daemon_config, start_origin

        from dragonfly2_tpu.common import tracing
        from dragonfly2_tpu.daemon.config import (
            SchedulerConfig as DaemonSchedCfg, TracingConfig)
        from dragonfly2_tpu.daemon.daemon import Daemon
        from dragonfly2_tpu.idl.messages import DeviceSink, DownloadRequest
        from dragonfly2_tpu.scheduler import Scheduler, SchedulerConfig
        from dragonfly2_tpu.scheduler.config import SeedPeerAddr

        trace_path = str(tmp_path / "traces.jsonl")
        old_tracer = tracing.TRACER
        tracing.TRACER = tracing.Tracer()
        tracing.configure = tracing.TRACER.configure

        async def go():
            data = os.urandom((10 << 20) + 777)     # 3 pieces
            origin, base = await start_origin({"w.bin": data})
            url = f"{base}/w.bin"
            seed_cfg = daemon_config(tmp_path, "seed")
            seed_cfg.is_seed = True
            seed = Daemon(seed_cfg)
            await seed.start()
            sched = Scheduler(SchedulerConfig(
                tracing_jsonl=trace_path,
                seed_peers=[SeedPeerAddr(
                    ip="127.0.0.1", rpc_port=seed.rpc.port,
                    download_port=seed.upload_server.port)]))
            await sched.start()
            leech_cfg = daemon_config(tmp_path, "leech")
            leech_cfg.scheduler = DaemonSchedCfg(
                addresses=[sched.address], schedule_timeout_s=20.0)
            leech_cfg.tracing = TracingConfig(enabled=True,
                                              jsonl_path=trace_path)
            leech = Daemon(leech_cfg)
            await leech.start()
            try:
                async for _ in leech.ptm.start_file_task(DownloadRequest(
                        url=url, output=str(tmp_path / "out.bin"),
                        disable_back_source=True, timeout_s=60.0,
                        device_sink=DeviceSink(enabled=True))):
                    pass
                assert (tmp_path / "out.bin").read_bytes() == data
                task_id = next(iter(leech.ptm._conductors))
                conductor = leech.ptm.conductor(task_id)
                assert conductor.traffic_p2p == len(data)
                # flight summary reached the scheduler's cluster view on
                # the terminal PeerResult (trails the client done event)
                for _ in range(100):
                    snap = sched.service.cluster.snapshot()
                    host = snap["hosts"].get("leech-127.0.0.1")
                    if host is not None and host["flights"] > 0:
                        break
                    await asyncio.sleep(0.05)
                assert host is not None and host["flights"] == 1
                assert host["last_flight"]["task_id"] == task_id
                assert host["last_flight"]["state"] == "success"
                assert snap["back_to_source_ratio"] == 0.0
            finally:
                tracing.TRACER.flush()
                await leech.stop()
                await sched.stop()
                await seed.stop()
                await origin.cleanup()

        try:
            asyncio.run(go())
            rows = [json.loads(ln) for ln in open(trace_path)]
            by_name: dict[str, list] = {}
            for r in rows:
                by_name.setdefault(r["name"], []).append(r)
            for needed in ("peertask", "sched.register", "sched.offer",
                           "piece.download", "upload.serve", "hbm.ingest"):
                assert needed in by_name, (needed, sorted(by_name))
            # the leecher's peertask trace id threads every layer
            task_traces = {r["trace_id"] for r in by_name["peertask"]}
            for name in ("sched.register", "sched.offer", "piece.download",
                         "upload.serve", "hbm.ingest"):
                joined = {r["trace_id"] for r in by_name[name]}
                assert joined & task_traces, (name, joined, task_traces)
        finally:
            tracing.TRACER.flush()
            tracing.TRACER = old_tracer
            tracing.configure = old_tracer.configure


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
