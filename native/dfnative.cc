// dfnative: C-ABI hot-path library for the TPU-native Dragonfly rebuild.
//
// Covers the work the reference delegates to native code (the Rust
// client-rs data plane) and Go's optimized runtime: piece hashing
// (sha256 / md5 / crc32c) and positioned file IO. Exposed as a plain C ABI
// consumed via ctypes (dragonfly2_tpu/storage/native.py).
//
// All hash implementations are from the public specifications
// (FIPS 180-4, RFC 1321, RFC 3720 / Castagnoli).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <unistd.h>
#include <cerrno>

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif

namespace {

// ---------------------------------------------------------------- sha256

struct Sha256 {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buf_len = 0;

  Sha256() {
    static const uint32_t init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                     0xa54ff53a, 0x510e527f, 0x9b05688c,
                                     0x1f83d9ab, 0x5be0cd19};
    memcpy(h, init, sizeof(h));
  }

  static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

  void block(const uint8_t* p) {
    static const uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
        0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
        0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
        0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
        0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
        0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
        0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
        0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
        0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[i * 4]) << 24) | (uint32_t(p[i * 4 + 1]) << 16) |
             (uint32_t(p[i * 4 + 2]) << 8) | uint32_t(p[i * 4 + 3]);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + s1 + ch + k[i] + w[i];
      uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* data, size_t n) {
    len += n;
    if (buf_len) {
      size_t take = 64 - buf_len;
      if (take > n) take = n;
      memcpy(buf + buf_len, data, take);
      buf_len += take;
      data += take;
      n -= take;
      if (buf_len == 64) {
        block(buf);
        buf_len = 0;
      }
    }
    while (n >= 64) {
      block(data);
      data += 64;
      n -= 64;
    }
    if (n) {
      memcpy(buf, data, n);
      buf_len = n;
    }
  }

  void final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buf_len != 56) update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bits >> (56 - i * 8));
    update(lenb, 8);
    for (int i = 0; i < 8; i++) {
      out[i * 4] = uint8_t(h[i] >> 24);
      out[i * 4 + 1] = uint8_t(h[i] >> 16);
      out[i * 4 + 2] = uint8_t(h[i] >> 8);
      out[i * 4 + 3] = uint8_t(h[i]);
    }
  }
};

// ---------------------------------------------------------------- md5

struct Md5 {
  uint32_t a0 = 0x67452301, b0 = 0xefcdab89, c0 = 0x98badcfe, d0 = 0x10325476;
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buf_len = 0;

  static uint32_t rotl(uint32_t x, int c) { return (x << c) | (x >> (32 - c)); }

  void block(const uint8_t* p) {
    static const uint32_t K[64] = {
        0xd76aa478, 0xe8c7b756, 0x242070db, 0xc1bdceee, 0xf57c0faf, 0x4787c62a,
        0xa8304613, 0xfd469501, 0x698098d8, 0x8b44f7af, 0xffff5bb1, 0x895cd7be,
        0x6b901122, 0xfd987193, 0xa679438e, 0x49b40821, 0xf61e2562, 0xc040b340,
        0x265e5a51, 0xe9b6c7aa, 0xd62f105d, 0x02441453, 0xd8a1e681, 0xe7d3fbc8,
        0x21e1cde6, 0xc33707d6, 0xf4d50d87, 0x455a14ed, 0xa9e3e905, 0xfcefa3f8,
        0x676f02d9, 0x8d2a4c8a, 0xfffa3942, 0x8771f681, 0x6d9d6122, 0xfde5380c,
        0xa4beea44, 0x4bdecfa9, 0xf6bb4b60, 0xbebfbc70, 0x289b7ec6, 0xeaa127fa,
        0xd4ef3085, 0x04881d05, 0xd9d4d039, 0xe6db99e5, 0x1fa27cf8, 0xc4ac5665,
        0xf4292244, 0x432aff97, 0xab9423a7, 0xfc93a039, 0x655b59c3, 0x8f0ccc92,
        0xffeff47d, 0x85845dd1, 0x6fa87e4f, 0xfe2ce6e0, 0xa3014314, 0x4e0811a1,
        0xf7537e82, 0xbd3af235, 0x2ad7d2bb, 0xeb86d391};
    static const int S[64] = {7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22,
                              7, 12, 17, 22, 5, 9,  14, 20, 5, 9,  14, 20,
                              5, 9,  14, 20, 5, 9,  14, 20, 4, 11, 16, 23,
                              4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23,
                              6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21,
                              6, 10, 15, 21};
    uint32_t m[16];
    for (int i = 0; i < 16; i++)
      m[i] = uint32_t(p[i * 4]) | (uint32_t(p[i * 4 + 1]) << 8) |
             (uint32_t(p[i * 4 + 2]) << 16) | (uint32_t(p[i * 4 + 3]) << 24);
    uint32_t A = a0, B = b0, C = c0, D = d0;
    for (int i = 0; i < 64; i++) {
      uint32_t F;
      int g;
      if (i < 16) { F = (B & C) | (~B & D); g = i; }
      else if (i < 32) { F = (D & B) | (~D & C); g = (5 * i + 1) % 16; }
      else if (i < 48) { F = B ^ C ^ D; g = (3 * i + 5) % 16; }
      else { F = C ^ (B | ~D); g = (7 * i) % 16; }
      F = F + A + K[i] + m[g];
      A = D; D = C; C = B;
      B = B + rotl(F, S[i]);
    }
    a0 += A; b0 += B; c0 += C; d0 += D;
  }

  void update(const uint8_t* data, size_t n) {
    len += n;
    if (buf_len) {
      size_t take = 64 - buf_len;
      if (take > n) take = n;
      memcpy(buf + buf_len, data, take);
      buf_len += take;
      data += take;
      n -= take;
      if (buf_len == 64) {
        block(buf);
        buf_len = 0;
      }
    }
    while (n >= 64) {
      block(data);
      data += 64;
      n -= 64;
    }
    if (n) {
      memcpy(buf, data, n);
      buf_len = n;
    }
  }

  void final(uint8_t out[16]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buf_len != 56) update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bits >> (i * 8));
    update(lenb, 8);
    uint32_t hs[4] = {a0, b0, c0, d0};
    for (int i = 0; i < 4; i++) {
      out[i * 4] = uint8_t(hs[i]);
      out[i * 4 + 1] = uint8_t(hs[i] >> 8);
      out[i * 4 + 2] = uint8_t(hs[i] >> 16);
      out[i * 4 + 3] = uint8_t(hs[i] >> 24);
    }
  }
};

// ---------------------------------------------------------------- crc32c

uint32_t crc32c_table[256];
bool crc32c_init_done = false;

void crc32c_init() {
  if (crc32c_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int j = 0; j < 8; j++)
      c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
    crc32c_table[i] = c;
  }
  crc32c_init_done = true;
}

uint32_t crc32c(const uint8_t* data, size_t n, uint32_t crc) {
  crc = crc ^ 0xFFFFFFFFu;
#if defined(__SSE4_2__)
  while (n >= 8) {
    crc = uint32_t(_mm_crc32_u64(crc, *reinterpret_cast<const uint64_t*>(data)));
    data += 8;
    n -= 8;
  }
  while (n--) crc = _mm_crc32_u8(crc, *data++);
#else
  crc32c_init();
  while (n--) crc = crc32c_table[(crc ^ *data++) & 0xFF] ^ (crc >> 8);
#endif
  return crc ^ 0xFFFFFFFFu;
}

void to_hex(const uint8_t* digest, size_t n, char* out) {
  static const char* hex = "0123456789abcdef";
  for (size_t i = 0; i < n; i++) {
    out[i * 2] = hex[digest[i] >> 4];
    out[i * 2 + 1] = hex[digest[i] & 0xF];
  }
  out[n * 2] = 0;
}

}  // namespace

extern "C" {

// Hex digest of data under algo ("sha256" | "md5" | "crc32c").
// Returns 0 on success, -1 on unknown algo / small buffer.
int df_hash(const char* algo, const uint8_t* data, size_t n, char* hex_out,
            size_t hex_cap) {
  if (strcmp(algo, "sha256") == 0) {
    if (hex_cap < 65) return -1;
    Sha256 h;
    h.update(data, n);
    uint8_t d[32];
    h.final(d);
    to_hex(d, 32, hex_out);
    return 0;
  }
  if (strcmp(algo, "md5") == 0) {
    if (hex_cap < 33) return -1;
    Md5 h;
    h.update(data, n);
    uint8_t d[16];
    h.final(d);
    to_hex(d, 16, hex_out);
    return 0;
  }
  if (strcmp(algo, "crc32c") == 0) {
    if (hex_cap < 9) return -1;
    uint32_t c = crc32c(data, n, 0);
    snprintf(hex_out, hex_cap, "%08x", c);
    return 0;
  }
  return -1;
}

// Chainable crc32c: feed chunks with the previous call's return as seed.
// Matches the pure-Python _crc32c_py(data, crc) contract.
uint32_t df_crc32c(const uint8_t* data, size_t n, uint32_t seed) {
  return crc32c(data, n, seed);
}

// ---------------------------------------------------------------- piece IO

// Verify-and-persist in ONE pass: pwrite() the piece at its content offset
// while folding the bytes into crc32c. The Python path hashes the buffer
// and then writes it (two full memory traversals plus file-object
// overhead); fusing them halves memory traffic on the piece-landing hot
// path. Returns 0 and the final crc via *crc_out, or -errno.
int df_piece_write(const char* path, uint64_t offset, const uint8_t* data,
                   size_t n, uint32_t* crc_out) {
  int fd = open(path, O_WRONLY);
  if (fd < 0) return -errno;
  size_t done = 0;
  uint32_t crc = 0;
  const size_t kChunk = 4u << 20;
  while (done < n) {
    size_t want = n - done < kChunk ? n - done : kChunk;
    ssize_t w = pwrite(fd, data + done, want, (off_t)(offset + done));
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;   // PEP 475 parity
      int err = errno ? errno : 5;
      close(fd);
      return -err;
    }
    crc = crc32c(data + done, (size_t)w, crc);
    done += (size_t)w;
  }
  close(fd);
  if (crc_out) *crc_out = crc;
  return 0;
}

// Fused SPAN landing: pwrite() a whole contiguous multi-piece span at its
// content offset through an ALREADY-OPEN fd (the Python side caches one
// per task — open/close per piece was measurable at fan-out) while folding
// each piece's crc32c in the SAME traversal. One buffer walk verifies and
// persists N pieces; per-piece crcs land in crcs_out[i] so the caller can
// reject a corrupt piece without failing its groupmates (the bytes of a
// rejected piece are on disk but never recorded, so the region stays
// "absent" and the retry re-writes it — same safety story as
// df_piece_write). Returns 0, or -errno on IO failure.
int df_span_write(int fd, uint64_t offset, const uint8_t* data,
                  const uint64_t* piece_sizes, size_t n_pieces,
                  uint32_t* crcs_out) {
  size_t pos = 0;
  const size_t kChunk = 4u << 20;
  for (size_t i = 0; i < n_pieces; i++) {
    size_t n = (size_t)piece_sizes[i];
    uint32_t crc = 0;
    size_t done = 0;
    while (done < n) {
      size_t want = n - done < kChunk ? n - done : kChunk;
      ssize_t w = pwrite(fd, data + pos + done, want,
                         (off_t)(offset + pos + done));
      if (w <= 0) {
        if (w < 0 && errno == EINTR) continue;   // PEP 475 parity
        return -(errno ? errno : 5);
      }
      crc = crc32c(data + pos + done, (size_t)w, crc);
      done += (size_t)w;
    }
    if (crcs_out) crcs_out[i] = crc;
    pos += n;
  }
  return 0;
}

// pread() a piece straight into the caller's buffer (no Python file
// object, no intermediate copies). Returns bytes read or -errno; short
// reads past EOF return what was available.
int64_t df_piece_read(const char* path, uint64_t offset, uint8_t* out,
                      size_t n) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -errno;
  size_t done = 0;
  while (done < n) {
    ssize_t r = pread(fd, out + done, n - done, (off_t)(offset + done));
    if (r < 0) {
      if (errno == EINTR) continue;            // PEP 475 parity
      int err = errno ? errno : 5;
      close(fd);
      return -err;
    }
    if (r == 0) break;
    done += (size_t)r;
  }
  close(fd);
  return (int64_t)done;
}



}  // extern "C"
