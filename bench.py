"""Benchmark: P2P fan-out aggregate throughput vs naive direct downloads.

Shape of BASELINE config #2 shrunk to one machine, with every component in
its OWN OS process (origin, scheduler, seed daemon, N leecher daemons —
sharing one event loop would measure the GIL, not the framework): an origin
serving a synthetic weights file, one seed daemon, a real scheduler, and N
leechers that must replicate the file with back-source disabled (every byte
rides the mesh). The baseline is N processes each pulling the whole file
straight from the origin — what a fleet without the framework does.

Piece stores live in tmpfs: the TPU-native terminal sink is HBM/host RAM
(tpu/hbm_sink.py), so a ~100 MB/s VM boot disk would measure itself.

Prints ONE JSON line:
  {"metric": ..., "value": GB/s aggregate delivered, "unit": "GB/s",
   "vs_baseline": ours / naive}
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import statistics
import subprocess
import sys
import tempfile
import time

# BENCH_DEBUG_DIR enables timelines/parent dumps (cheap); BENCH_LOG_DEBUG
# additionally turns on DEBUG logging (expensive — distorts the measurement
# on CPU-bound hosts; keep off unless chasing a specific trace)
logging.basicConfig(
    level=logging.DEBUG if os.environ.get("BENCH_LOG_DEBUG") else logging.WARNING,
    stream=sys.stderr)

SIZE_MB = int(os.environ.get("BENCH_SIZE_MB", "128"))
N_LEECHERS = int(os.environ.get("BENCH_LEECHERS", "16"))
ORIGIN_MBPS = float(os.environ.get("BENCH_ORIGIN_MBPS", "64"))
# per-host upload NIC model (MB/s). On one machine loopback is ~free, which
# makes a star (seed serves everyone) look optimal and measures nothing; the
# cap restores the real constraint — each host's egress bandwidth — so the
# mesh only wins by actually fanning out through intermediate peers.
NIC_MBPS = float(os.environ.get("BENCH_NIC_MBPS", "128"))
REPO = os.path.dirname(os.path.abspath(__file__))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def ensure_native() -> None:
    so = os.path.join(REPO, "native", "build", "libdfnative.so")
    if not os.path.exists(so):
        subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                       capture_output=True, check=False)
    # environments with PYTHONDONTWRITEBYTECODE make every spawned role
    # re-compile the whole package (~170 modules, seconds per process, ×17
    # processes): compile once so the .pyc cache serves the fleet
    import compileall
    compileall.compile_dir(os.path.join(REPO, "dragonfly2_tpu"),
                           quiet=2, workers=0)


def base_tmp() -> str:
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


# ======================================================================
# worker roles (each runs in its own process: python bench.py --role X)
# ======================================================================

async def role_origin(path: str, mbps: float) -> None:
    """Serve ``path`` with Range support, paced at ``mbps`` MB/s total.

    The cap models the real scarce resource — origin/WAN/GCS egress per
    cluster (BASELINE's "% origin egress saved"). An uncapped loopback
    origin would make any P2P layer look like pure overhead, which is not
    the deployment the reference or this framework exists for. Tracks bytes
    served at /__stats__.
    """
    from aiohttp import web

    from dragonfly2_tpu.common.piece import parse_http_range
    from dragonfly2_tpu.common.rate import TokenBucket

    size = os.path.getsize(path)
    bucket = TokenBucket(mbps * 1e6, burst=4e6) if mbps > 0 else None
    served = {"bytes": 0}

    async def handle(request: web.Request):
        if request.path == "/__stats__":
            return web.json_response(served)
        start, length = 0, size
        status, headers = 200, {"Accept-Ranges": "bytes",
                                "Content-Length": "0"}
        rng = request.headers.get("Range")
        if rng:
            r = parse_http_range(rng, size)
            start, length = r.start, r.length
            status = 206
            headers["Content-Range"] = f"bytes {r.start}-{r.end-1}/{size}"
        headers["Content-Length"] = str(length)
        if request.method == "HEAD":
            # NEVER write a body for HEAD: a manually-streamed body poisons
            # the keep-alive connection (the client pools it as clean, the
            # stale body bytes then hang the next GET that reuses it)
            return web.Response(status=status, headers=headers)
        resp = web.StreamResponse(status=status, headers=headers)
        await resp.prepare(request)
        with open(path, "rb") as f:
            f.seek(start)
            remaining = length
            while remaining > 0:
                chunk = f.read(min(1 << 20, remaining))
                if not chunk:
                    break
                if bucket is not None:
                    await bucket.acquire(len(chunk))
                await resp.write(chunk)
                served["bytes"] += len(chunk)
                remaining -= len(chunk)
        await resp.write_eof()
        return resp

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", handle)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    from dragonfly2_tpu.common.aiohttp_util import resolve_port
    print(json.dumps({"port": resolve_port(runner)}), flush=True)
    await asyncio.Event().wait()


async def role_seed(workdir: str) -> None:
    from dragonfly2_tpu.daemon.config import (DaemonConfig, StorageSection,
                                              UploadConfig)
    from dragonfly2_tpu.daemon.daemon import Daemon

    cfg = DaemonConfig(workdir=workdir, host_ip="127.0.0.1", hostname="seed",
                       is_seed=True,
                       upload=UploadConfig(
                           rate_limit_bps=int(NIC_MBPS * 1e6),
                           # live /debug/{stacks,profile} on the upload port
                           # for wave-stall investigations
                           debug_endpoints=bool(
                               os.environ.get("BENCH_DEBUG_DIR"))),
                       storage=StorageSection(gc_interval_s=3600))
    daemon = Daemon(cfg)
    await daemon.start()
    print(json.dumps({"rpc_port": daemon.rpc.port,
                      "download_port": daemon.upload_server.port}), flush=True)
    await asyncio.Event().wait()


async def role_scheduler(seed_rpc: int, seed_dl: int) -> None:
    from dragonfly2_tpu.scheduler import Scheduler, SchedulerConfig
    from dragonfly2_tpu.scheduler.config import SeedPeerAddr

    sched = Scheduler(SchedulerConfig(seed_peers=[SeedPeerAddr(
        ip="127.0.0.1", rpc_port=seed_rpc, download_port=seed_dl)]))
    await sched.start()
    print(json.dumps({"addr": sched.address}), flush=True)
    await asyncio.Event().wait()


async def role_leecher(workdir: str, name: str, sched_addr: str,
                       url: str) -> None:
    from dragonfly2_tpu.daemon.config import (DaemonConfig,
                                              SchedulerConfig as DSched,
                                              StorageSection, TracingConfig,
                                              UploadConfig)
    from dragonfly2_tpu.daemon.daemon import Daemon
    from dragonfly2_tpu.idl.messages import DownloadRequest
    from dragonfly2_tpu.rpc.client import Channel, ServiceClient

    dbg = os.environ.get("BENCH_DEBUG_DIR")
    cfg = DaemonConfig(workdir=workdir, host_ip="127.0.0.1", hostname=name,
                       scheduler=DSched(addresses=[sched_addr],
                                        schedule_timeout_s=60.0),
                       upload=UploadConfig(rate_limit_bps=int(NIC_MBPS * 1e6)),
                       storage=StorageSection(gc_interval_s=3600),
                       tracing=TracingConfig(
                           enabled=bool(dbg),
                           jsonl_path=dbg and os.path.join(
                               dbg, f"{name}.traces.jsonl") or ""))
    daemon = Daemon(cfg)
    await daemon.start()
    print("READY", flush=True)
    await asyncio.get_running_loop().run_in_executor(None, sys.stdin.readline)

    ch = Channel(f"unix:{daemon.unix_sock}")
    client = ServiceClient(ch, "df.daemon.Daemon")
    out = os.path.join(workdir, "replica.bin")
    t0 = time.monotonic()
    task_id = None
    timeline: list[tuple[float, int]] = []
    sampler = None
    if os.environ.get("BENCH_DEBUG_DIR"):
        async def sample() -> None:
            while True:
                c = daemon.ptm.conductor(task_id) if task_id else None
                n_seed = n_known = -1
                if c is not None:
                    n = len(c.ready)
                    if c.storage is not None:
                        n_seed = sum(1 for p in c.storage.md.pieces.values()
                                     if "seed" in (p.source or ""))
                    eng = c._p2p_engine
                    if eng is not None:
                        n_known = len(eng.dispatcher._pieces) + n
                else:
                    n = -1
                timeline.append((time.monotonic() - t0, n, n_seed, n_known))
                await asyncio.sleep(0.1)
        sampler = asyncio.get_running_loop().create_task(sample())
    async for resp in client.unary_stream("Download", DownloadRequest(
            url=url, output=out, disable_back_source=True, timeout_s=600.0)):
        task_id = resp.task_id or task_id
    elapsed = time.monotonic() - t0
    if sampler is not None:
        sampler.cancel()
        print(json.dumps({"timeline": [[round(t, 2), *rest]
                                       for t, *rest in timeline]}),
              file=sys.stderr, flush=True)
    size = os.path.getsize(out)
    sources: dict[str, int] = {}
    engine_state = {}
    conductor = daemon.ptm.conductor(task_id) if task_id else None
    engine = conductor._p2p_engine if conductor is not None else None
    if conductor is not None and conductor.storage is not None:
        for p in conductor.storage.md.pieces.values():
            key = (p.source or "origin")[-10:]
            sources[key] = sources.get(key, 0) + 1
        if engine is not None and os.environ.get("BENCH_DEBUG_DIR"):
            engine_state = {
                pid[-10:]: {"ejected": st.ejected,
                            "nspb": round(st.ns_per_byte, 1),
                            "try": st.attempts, "ann": st.announced}
                for pid, st in engine.dispatcher.parents.items()}
    out_msg = {"elapsed": elapsed, "bytes": size, "sources": sources,
               "name": name}
    if engine is not None:
        # structural convoy accounting: fraction of worker-seconds spent
        # parked in the dispatcher, and the slice of that waiting on a
        # busy seed (see PieceDispatcher.wait_stats)
        ws = dict(engine.dispatcher.wait_stats)
        worker_s = max(elapsed * engine.parallelism, 1e-9)
        out_msg["wait"] = {k: round(v, 3) for k, v in ws.items()}
        out_msg["idle_frac"] = round(sum(ws.values()) / worker_s, 4)
        out_msg["seed_wait_frac"] = round(ws["seed_busy_s"] / worker_s, 4)
    if engine_state:
        out_msg["parents"] = engine_state
    print(json.dumps(out_msg), flush=True)
    # stay up until the whole wave is done: a real fleet's daemons keep
    # serving after their own download completes — early exit here would
    # rip parents out from under the stragglers
    await asyncio.get_running_loop().run_in_executor(None, sys.stdin.readline)
    await ch.close()
    await daemon.stop()


async def role_direct(workdir: str, url: str) -> None:
    import aiohttp

    print("READY", flush=True)
    await asyncio.get_running_loop().run_in_executor(None, sys.stdin.readline)
    t0 = time.monotonic()
    got = 0
    out = os.path.join(workdir, "direct.bin")
    async with aiohttp.ClientSession() as session:
        async with session.get(url) as resp:
            with open(out, "wb") as f:
                async for chunk in resp.content.iter_chunked(1 << 20):
                    f.write(chunk)
                    got += len(chunk)
    elapsed = time.monotonic() - t0
    print(json.dumps({"elapsed": elapsed, "bytes": got}), flush=True)


# ======================================================================
# TPU device-ingest phase (runs in the MAIN process on the real chip)
# ======================================================================

async def tpu_ingest_bench(data_path: str, workdir: str) -> dict:
    """BASELINE config #4's device leg: origin → pieces → device_put →
    result() through the real daemon path (conductor + DeviceIngest), on
    whatever jax.devices() provides. Reports:

      device_ingest_gbps   — pure host-buffer → HBM transfer bandwidth
      ingest_overlap_eff   — fraction of that transfer time hidden behind
                             the download (1.0 = fully overlapped)
    """
    import numpy as np

    from aiohttp import web

    import jax

    from dragonfly2_tpu.common.piece import parse_http_range
    from dragonfly2_tpu.daemon.config import DaemonConfig, StorageSection
    from dragonfly2_tpu.daemon.daemon import Daemon
    from dragonfly2_tpu.idl.messages import DeviceSink

    size = os.path.getsize(data_path)

    async def handle(request: web.Request):
        start, length = 0, size
        status, headers = 200, {"Accept-Ranges": "bytes"}
        rng = request.headers.get("Range")
        if rng:
            r = parse_http_range(rng, size)
            start, length = r.start, r.length
            status = 206
            headers["Content-Range"] = f"bytes {r.start}-{r.end-1}/{size}"
        headers["Content-Length"] = str(length)
        if request.method == "HEAD":
            # see role_origin: a HEAD body poisons the pooled connection
            return web.Response(status=status, headers=headers)
        resp = web.StreamResponse(status=status, headers=headers)
        await resp.prepare(request)
        with open(data_path, "rb") as f:
            f.seek(start)
            remaining = length
            while remaining > 0:
                chunk = f.read(min(1 << 20, remaining))
                if not chunk:
                    break
                await resp.write(chunk)
                remaining -= len(chunk)
        await resp.write_eof()
        return resp

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", handle)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    from dragonfly2_tpu.common.aiohttp_util import resolve_port
    base = f"http://127.0.0.1:{resolve_port(runner)}"

    daemon = Daemon(DaemonConfig(
        workdir=os.path.join(workdir, "tpudaemon"), host_ip="127.0.0.1",
        hostname="tpubench", storage=StorageSection(gc_interval_s=3600)))
    await daemon.start()
    try:
        # 1) pure device transfer bandwidth: same bytes, one put per DMA unit
        buf = np.fromfile(data_path, dtype=np.uint8)
        dev = jax.devices()[0]
        jax.device_put(buf[:1 << 20], dev).block_until_ready()   # warm path
        t0 = time.monotonic()
        put = jax.device_put(buf, dev)
        put.block_until_ready()
        t_ingest = time.monotonic() - t0
        del put

        async def run_download(url: str, sink: DeviceSink | None):
            """Returns (total_wall, hidden_fraction). hidden is measured
            STRUCTURALLY inside the one run — the fraction of device-
            transfer time that executed before the download's last byte —
            because on this host single-download wall clocks swing ±50%
            (VM jitter), far more than the transfer time being hidden, so
            subtracting wall clocks of separate runs measures only noise."""
            t0 = time.monotonic()
            task_id, ingest = await _run_sink_task(
                daemon, url, os.path.join(workdir, "tpu.out"), sink)
            t_dl_end = time.monotonic()
            hidden = 0.0
            if ingest is not None:
                # block on the last DMA off-loop (result() is blocking)
                await asyncio.to_thread(ingest.result)
                spans = list(ingest.transfer_spans)
                total = sum(e - s for s, e in spans)
                if total > 0:
                    hidden = sum(max(0.0, min(e, t_dl_end) - s)
                                 for s, e in spans) / total
            elapsed = time.monotonic() - t0
            # 6 runs over distinct URLs: drop each task's pieces + device
            # arrays before the next, or peak residency is 6x file size
            if task_id is not None:
                await daemon.ptm.delete_task(task_id)
            return elapsed, hidden

        t_dl = statistics.median(
            [(await run_download(f"{base}/plain{i}.bin", None))[0]
             for i in range(3)])
        sink_runs = [await run_download(f"{base}/sink{i}.bin",
                                        DeviceSink(enabled=True))
                     for i in range(3)]
        t_overlap = statistics.median([t for t, _ in sink_runs])
        hidden = statistics.median([h for _, h in sink_runs])
        gbps = size / 1e9 / t_ingest
        log(f"tpu ingest: pure device_put {gbps:.2f} GB/s ({t_ingest:.2f}s), "
            f"download {t_dl:.2f}s, with sink {t_overlap:.2f}s -> "
            f"{hidden:.0%} of device transfer ran during the download "
            f"[{jax.devices()[0].platform}]")
        train_stats = await _train_during_ingest(daemon, base, workdir, size)
        return {"device_ingest_gbps": round(gbps, 3),
                "ingest_overlap_efficiency": round(hidden, 3),
                "device_platform": jax.devices()[0].platform,
                **train_stats}
    finally:
        await daemon.stop()
        await runner.cleanup()


async def _run_sink_task(daemon, url: str, out_path: str, sink):
    """One download task's lifecycle through the real daemon path; returns
    (task_id, device_ingest | None). Both overlap measurements share this
    so a fix to task collection applies to each exactly once."""
    from dragonfly2_tpu.idl.messages import DownloadRequest

    task_id = None
    async for resp in daemon.ptm.start_file_task(DownloadRequest(
            url=url, output=out_path, device_sink=sink, timeout_s=600.0)):
        task_id = resp.task_id or task_id
    conductor = daemon.ptm.conductor(task_id) if task_id else None
    ingest = conductor.device_ingest if conductor is not None else None
    return task_id, ingest if sink is not None else None


async def _train_during_ingest(daemon, base: str, workdir: str,
                               size: int) -> dict:
    """BASELINE config #4's actual claim: prefetch into HBM *during* JAX
    training. Runs a jitted train-step loop on the same device while
    ``DeviceIngest`` streams the file through the real daemon path, and
    reports how much the training loop slowed down plus the DMA-active
    ingest bandwidth achieved concurrently. On real TPU the device_put
    contends with the train step for DMA engines + HBM bandwidth — this is
    the number the README's overlap story rests on.
    """
    import threading

    import jax

    from dragonfly2_tpu.idl.messages import DeviceSink
    from dragonfly2_tpu.trainer import models

    key = jax.random.PRNGKey(0)
    params = models.init_mlp(key)
    opt = models.make_optimizer()
    opt_state = opt.init(params)
    batch = models.synthetic_mlp_batch(key, 4096)
    train_step = models.make_train_step(models.mlp_loss, opt)
    params, opt_state, loss = train_step(params, opt_state, batch)
    jax.block_until_ready(loss)                      # compile outside timing

    state = {"params": params, "opt": opt_state}

    def steps_per_s(duration_s: float, stop: threading.Event | None = None,
                    stamps: list | None = None) -> tuple[float, int]:
        n = 0
        t0 = time.monotonic()
        while time.monotonic() - t0 < duration_s \
                and (stop is None or not stop.is_set()):
            state["params"], state["opt"], loss = train_step(
                state["params"], state["opt"], batch)
            jax.block_until_ready(loss)
            n += 1
            if stamps is not None:
                stamps.append(time.monotonic())
        dt = time.monotonic() - t0
        return n / dt if dt > 0 else 0.0, n

    base_sps, _ = steps_per_s(3.0)

    stop = threading.Event()
    stamps: list[float] = []
    train_task = asyncio.create_task(
        asyncio.to_thread(steps_per_s, 600.0, stop, stamps))
    dma_active = 0.0
    streamed = 0
    windows: list[tuple[float, float]] = []
    try:
        # stream until the train loop has a statistically usable window
        # (a single fast download can be < a handful of steps): up to 3
        # serial files, each a distinct task
        for i in range(3):
            t_w0 = time.monotonic()
            task_id, ingest = await _run_sink_task(
                daemon, f"{base}/train-overlap{i}.bin",
                os.path.join(workdir, "train-overlap.out"),
                DeviceSink(enabled=True))
            if ingest is not None:
                await asyncio.to_thread(ingest.result)
                dma_active += sum(e - s for s, e in ingest.transfer_spans)
                streamed += size
                # window closes at last-DMA-done, BEFORE the bookkeeping
                # (delete_task, loop checks) — the slowdown number must
                # only average steps that ran against live ingest, not the
                # gaps (and a failed sink task contributes no window)
                windows.append((t_w0, time.monotonic()))
            if task_id is not None:
                await daemon.ptm.delete_task(task_id)
            in_window = sum(1 for t in stamps
                            if any(s <= t <= e for s, e in windows))
            if in_window >= 15 or stop.is_set() or train_task.done():
                break
    finally:
        stop.set()
    await train_task
    window_s = sum(e - s for s, e in windows)
    during_steps = sum(1 for t in stamps
                       if any(s <= t <= e for s, e in windows))
    during_sps = during_steps / window_s if window_s > 0 else 0.0
    slowdown = (1.0 - during_sps / base_sps) if base_sps > 0 else 0.0
    gbps_during = streamed / 1e9 / dma_active if dma_active > 0 else 0.0
    log(f"train during ingest: {base_sps:.1f} -> {during_sps:.1f} steps/s "
        f"({slowdown:.1%} slowdown, {during_steps} steps while streaming), "
        f"ingest DMA-active bandwidth {gbps_during:.2f} GB/s")
    return {"train_steps_per_s_baseline": round(base_sps, 2),
            "train_steps_per_s_during_ingest": round(during_sps, 2),
            "train_step_slowdown_pct": round(100 * slowdown, 1),
            "device_ingest_gbps_during_train": round(gbps_during, 3)}


# ======================================================================
# orchestration
# ======================================================================

class Proc:
    def __init__(self, args: list[str], stderr_path: str | None = None,
                 env: dict | None = None):
        stderr = (open(stderr_path, "w") if stderr_path
                  else subprocess.DEVNULL)
        self.p = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "bench.py"), *args],
            stdout=subprocess.PIPE, stderr=stderr,
            stdin=subprocess.PIPE, text=True, cwd=REPO,
            env={**os.environ, **env} if env else None)

    def read_json(self, timeout: float = 120.0):
        line = self._read_line(timeout)
        return json.loads(line)

    def wait_ready(self, timeout: float = 240.0) -> None:
        # generous: 16 fresh interpreters importing on one contended vCPU
        # can legitimately take minutes to all come up
        line = self._read_line(timeout)
        assert line.strip() == "READY", f"unexpected: {line!r}"

    def _read_line(self, timeout: float) -> str:
        import select
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError("worker did not report in time")
            # select before readline: readline() itself blocks and would
            # defeat the deadline when a worker hangs without printing
            ready, _, _ = select.select([self.p.stdout], [], [],
                                        min(remaining, 1.0))
            if ready:
                line = self.p.stdout.readline()
                if line:
                    return line
            if self.p.poll() is not None:
                raise RuntimeError(f"worker died: rc={self.p.returncode}")

    def go(self) -> None:
        try:
            self.p.stdin.write("\n")
            self.p.stdin.flush()
        except (BrokenPipeError, OSError):
            pass   # role already exited (direct pulls don't linger)

    def kill(self) -> None:
        if self.p.poll() is None:
            self.p.kill()
            self.p.wait()


def _cpu_sample() -> tuple[float, float]:
    """(busy_jiffies, total_jiffies) across the host."""
    with open("/proc/stat") as f:
        parts = f.readline().split()[1:]
    vals = [float(x) for x in parts]
    idle = vals[3] + (vals[4] if len(vals) > 4 else 0.0)
    return sum(vals) - idle, sum(vals)


def run_wave(procs: list[Proc]) -> tuple[float, list[float], float, dict]:
    """READY-barrier, then GO all; returns (max elapsed, per-proc
    seed-sourced piece fractions, host CPU utilization during the wave,
    wait accounting {idle_fracs, seed_wait_fracs}).

    The utilization is reported so sublinearity reads honestly: on a
    host with fewer cores than daemons (the 1-vCPU bench VM) a 2x-work
    wave on a saturated CPU takes ~2x wall-clock regardless of scheduling
    quality — the NICs in the model scale with peer count, the cores
    running the daemons do not. The wait accounting separates those: a
    convoy shows up as workers idle in the dispatcher, CPU saturation as
    idle ≈ 0 with util ≈ 1.
    """
    for p in procs:
        p.wait_ready()
    cpu0 = _cpu_sample()
    for p in procs:
        p.go()
    results = [p.read_json(timeout=600.0) for p in procs]
    cpu1 = _cpu_sample()
    cpu_util = ((cpu1[0] - cpu0[0]) / max(cpu1[1] - cpu0[1], 1.0))
    seed_fracs: list[float] = []
    waits = {"idle_fracs": [], "seed_wait_fracs": []}
    for r in results:
        assert r["bytes"] == SIZE_MB << 20, f"short transfer: {r}"
        if "idle_frac" in r:
            waits["idle_fracs"].append(r["idle_frac"])
            waits["seed_wait_fracs"].append(r["seed_wait_frac"])
        if r.get("sources"):
            log(f"  piece sources: {r['sources']} ({r['elapsed']:.2f}s"
                + (f", idle {r['idle_frac']:.0%}" if "idle_frac" in r else "")
                + ")"
                + (f" parents={r['parents']}" if r.get("parents") else ""))
            total = sum(r["sources"].values())
            from_seed = sum(n for k, n in r["sources"].items() if "seed" in k)
            seed_fracs.append(from_seed / total if total else 0.0)
    for p in procs:
        p.go()   # whole wave done: daemons may now exit
    return max(r["elapsed"] for r in results), seed_fracs, cpu_util, waits


def _clean_wave_dirs(workdir: str, tag: str, n: int) -> None:
    """Drop a wave's piece stores + replicas NOW: workdirs live in
    /dev/shm (RAM), and N waves x 16 leechers x 2 file-size copies
    accumulate tens of GB of tmpfs pages — which measurably slowed every
    later wave on the 1-vCPU bench VM (the r04 escalating-wave mystery:
    13s -> 67s across identical waves, cured by this cleanup)."""
    import shutil
    dbg = os.environ.get("BENCH_DEBUG_DIR")
    for i in range(n):
        d = os.path.join(workdir, f"{tag}{i}")
        if dbg:
            # keep logs/ — the finally-block forensics copytree needs the
            # per-daemon file logs; drop only the bulky payload dirs
            for sub in ("data", "cache", "run"):
                shutil.rmtree(os.path.join(d, sub), ignore_errors=True)
            try:
                os.unlink(os.path.join(d, "replica.bin"))
            except OSError:
                pass
        else:
            shutil.rmtree(d, ignore_errors=True)


def fanout_wave(workdir: str, tag: str, n: int, sched_addr: str,
                url: str, daemons: list["Proc"], *,
                origin_bytes_fn=None, _retry: bool = True,
                env: dict | None = None
                ) -> tuple[float, list[float], float, dict, int]:
    """Returns (max elapsed, seed fractions, cpu util, wait accounting,
    origin egress).

    Egress is sampled INSIDE the wave (around the attempt that succeeded)
    so an aborted first attempt's partial origin pulls don't inflate the
    successful retry's egress-saved accounting."""
    pre = origin_bytes_fn() if origin_bytes_fn else 0
    leechers = [Proc(["--role", "leecher",
                      os.path.join(workdir, f"{tag}{i}"), f"{tag}leech{i}",
                      sched_addr, url],
                     stderr_path=os.environ.get("BENCH_DEBUG_DIR") and
                     os.path.join(os.environ["BENCH_DEBUG_DIR"],
                                  f"{tag}{i}.err"),
                     env=env)
                for i in range(n)]
    daemons.extend(leechers)   # killed on any failure path
    try:
        result = run_wave(leechers)
    except (TimeoutError, RuntimeError) as exc:
        # a straggler spawn on a contended host (16 interpreters on one
        # vCPU) must not abort the whole bench — kill this wave's procs,
        # free its tmpfs, and retry ONCE on a fresh tag + task
        for p in leechers:
            p.kill()
        _clean_wave_dirs(workdir, tag, n)
        if not _retry:
            raise
        log(f"wave {tag} spawn failed ({exc}); retrying once")
        return fanout_wave(workdir, f"{tag}r", n, sched_addr,
                           url + ".retry", daemons,
                           origin_bytes_fn=origin_bytes_fn, _retry=False,
                           env=env)
    # reap this wave's processes BEFORE the caller starts the next one:
    # 16 daemons' teardown (channel close, daemon.stop, interpreter exit)
    # costs seconds of CPU that would otherwise bleed into the next timed
    # wave on a core-bound host
    for p in leechers:
        try:
            p.p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
    _clean_wave_dirs(workdir, tag, n)
    egress = (origin_bytes_fn() - pre) if origin_bytes_fn else 0
    return (*result, egress)


LAST_GOOD_TPU = os.path.join(REPO, "BENCH_TPU_LAST_GOOD.json")


def role_tpu(data_path: str, workdir: str) -> None:
    """Run the full TPU ingest phase in this (fresh) process and print one
    JSON line. Exits rc=3 quickly when the accelerator runtime is wedged so
    the parent's retry loop can try again later instead of burning its
    whole deadline inside one attempt.

    ``BENCH_TPU_FORCE_CPU=1`` pins the phase at the CPU backend (the
    numbers stay honest — ``device_platform`` labels them): useful for
    exercising the phase when the accelerator tunnel is down."""
    # this child exists to DETECT RECOVERY: the host wedge marker must not
    # short-circuit its probe into a stale 'still down' answer
    os.environ["DF_TOPOLOGY_WEDGE_CACHE"] = "0"
    if os.environ.get("BENCH_TPU_FORCE_CPU"):
        import jax
        jax.config.update("jax_platforms", "cpu")
    from dragonfly2_tpu.tpu.topology import probe_jax_devices

    status, payload = probe_jax_devices(timeout_s=30.0)
    if status != "ok":
        log(f"tpu probe: {status} ({payload})")
        raise SystemExit(3)
    stats = asyncio.run(tpu_ingest_bench(data_path, workdir))
    print(json.dumps(stats), flush=True)


def _tpu_phase_with_retry(data_path: str, workdir: str) -> dict:
    """Attempt the TPU phase until it succeeds or the deadline passes; on
    success persist the numbers (timestamped, platform-labeled) to
    ``BENCH_TPU_LAST_GOOD.json``; on total failure fall back to that file
    so a tunnel wedged at snapshot time cannot erase real measurements —
    four rounds of bench artifacts carried no on-chip number for exactly
    this reason (VERDICT r04 weak #2)."""
    deadline = time.monotonic() + float(
        os.environ.get("BENCH_TPU_DEADLINE_S", "420"))
    attempt = 0
    while True:
        attempt += 1
        budget = deadline - time.monotonic()
        if budget <= 0 and attempt > 1:
            break
        try:
            # bounded per attempt: the probe exits rc=3 in ~30s on a wedged
            # runtime, but the tunnel can wedge AFTER the probe passes and
            # hang the child mid-phase — the cap keeps one bad attempt from
            # stalling the bench for longer than the phase could ever take
            proc = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py"),
                 "--role", "tpu", data_path, workdir],
                capture_output=True, text=True, cwd=REPO,
                # clamp to the remaining deadline so one post-probe wedge
                # can't overshoot a short configured deadline 10x, with a
                # floor that still lets a healthy phase finish
                timeout=min(600.0, max(deadline - time.monotonic(), 120.0)))
        except subprocess.TimeoutExpired:
            log(f"tpu phase attempt {attempt}: timed out mid-phase")
            continue
        sys.stderr.write(proc.stderr)
        if proc.returncode == 0:
            try:
                stats = json.loads(proc.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                log(f"tpu phase attempt {attempt}: unparseable output")
                break
            stats["tpu_measured_at"] = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
            # a cpu-backend run (forced, or an accelerator-less host) must
            # never clobber preserved on-chip numbers — that would recreate
            # the "real measurements erased" failure this file prevents
            try:
                with open(LAST_GOOD_TPU) as f:
                    prior = json.load(f)
            except (OSError, ValueError):
                prior = {}
            if stats.get("device_platform") == "cpu" \
                    and prior.get("device_platform") not in (None, "cpu"):
                log("tpu phase: cpu-backend numbers NOT persisted over "
                    f"on-chip last-good from {prior.get('tpu_measured_at')}")
            else:
                try:
                    with open(LAST_GOOD_TPU, "w") as f:
                        json.dump(stats, f, indent=1)
                except OSError:
                    pass
            return stats
        if proc.returncode == 3:    # wedged runtime: cheap retry
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                log("tpu ingest phase unavailable: accelerator runtime is "
                    "not answering (deadline reached)")
                break
            wait = min(30.0, remaining)
            log(f"tpu phase attempt {attempt}: runtime wedged; retrying in "
                f"{wait:.0f}s ({remaining:.0f}s of deadline left)")
            time.sleep(wait)
            continue
        log(f"tpu phase attempt {attempt}: failed rc={proc.returncode}")
        break
    try:
        with open(LAST_GOOD_TPU) as f:
            stale = json.load(f)
    except (OSError, ValueError):
        return {}
    stale["tpu_stats_stale"] = True
    log(f"tpu phase: reporting last-good measurements from "
        f"{stale.get('tpu_measured_at', '?')} "
        f"[{stale.get('device_platform', '?')}]")
    return stale


def _calibrate() -> float:
    """Fixed-work CPU probe (GB/s of sha256 over 64 MiB): the bench host's
    effective speed swings ~2-3x between runs (shared-host phases — the pure
    device_put figure shows the same oscillation), so every run records the
    host speed it saw alongside the numbers it produced."""
    import hashlib
    buf = b"\xa5" * (64 << 20)
    t0 = time.monotonic()
    hashlib.sha256(buf).hexdigest()
    return round(len(buf) / 1e9 / (time.monotonic() - t0), 3)


def _calibrate_mp(workers: int = 4) -> float:
    """Aggregate GB/s of ``workers`` parallel sha256 processes. The
    single-thread calib stays flat while co-tenant load slows saturated
    multi-process waves 2x (r5: full waves 12s -> 27s at constant
    single-thread calib) — THIS probe captures the contention those waves
    actually run under, so cross-run wave comparisons can be normalized."""
    # readiness handshake then a SHARED start epoch: without the barrier,
    # spawn skew (interpreter startup is seconds on this host) lets the
    # windows land disjoint and the "contended" sum approaches N x
    # single-thread. Each worker reports when its window actually opened
    # so stragglers can be excluded from the sum. Any failure degrades to
    # 0.0 — this probe must never cost the run its one JSON output line.
    code = ("import hashlib,sys,time\n"
            "print('ready', flush=True)\n"
            "start = float(sys.stdin.readline())\n"
            "time.sleep(max(0.0, start - time.time()))\n"
            "opened = time.time()\n"
            "buf = b'\\xa5' * (8 << 20)\n"
            "n, t0 = 0, time.monotonic()\n"
            "while time.monotonic() - t0 < 1.5:\n"
            "    hashlib.sha256(buf).hexdigest(); n += 1\n"
            "print(opened, n * (8 << 20) / (time.monotonic() - t0))")
    procs = []
    try:
        for _ in range(workers):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", code], stdin=subprocess.PIPE,
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True))
        for p in procs:
            if p.stdout.readline().strip() != "ready":
                raise RuntimeError("calib worker failed to start")
        start_at = time.time() + 0.5
        for p in procs:
            p.stdin.write(f"{start_at}\n")
            p.stdin.flush()
        results = []
        for p in procs:
            opened, rate = p.stdout.readline().split()
            results.append((float(opened), float(rate)))
            p.wait(timeout=30)
    except Exception:  # noqa: BLE001 - diagnostic probe only
        return 0.0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    on_time = [rate for opened, rate in results
               if opened <= start_at + 1.0]
    if len(on_time) < 2:
        return 0.0       # windows didn't overlap: no contention measured
    return round(sum(on_time) / 1e9, 3)


def main() -> None:
    ensure_native()
    workdir = tempfile.mkdtemp(prefix="dfbench-", dir=base_tmp())
    data_path = os.path.join(workdir, "weights.bin")
    with open(data_path, "wb") as f:
        remaining = SIZE_MB << 20
        while remaining > 0:
            n = min(remaining, 64 << 20)
            f.write(os.urandom(n))
            remaining -= n

    daemons: list[Proc] = []
    try:
        origin = Proc(["--role", "origin", data_path, str(ORIGIN_MBPS)])
        daemons.append(origin)
        origin_base = f"http://127.0.0.1:{origin.read_json()['port']}"
        url = f"{origin_base}/weights.bin"

        import urllib.request

        def origin_bytes() -> int:
            with urllib.request.urlopen(f"{origin_base}/__stats__") as r:
                return json.loads(r.read())["bytes"]

        log(f"bench: {SIZE_MB} MiB x {N_LEECHERS} leechers, origin "
            f"{ORIGIN_MBPS:.0f} MB/s, per-host upload NIC {NIC_MBPS:.0f} MB/s "
            f"(multi-process)")
        # direct baseline: origin-capped, so aggregate throughput is the
        # origin rate no matter how many clients pull — 4 processes measure
        # it; egress for N direct clients is N x size by definition.
        n_direct = min(N_LEECHERS, 4)
        direct = [Proc(["--role", "direct", os.path.join(workdir, f"d{i}"),
                        url]) for i in range(n_direct)]
        daemons.extend(direct)   # killed on any failure path
        for i in range(n_direct):
            os.makedirs(os.path.join(workdir, f"d{i}"), exist_ok=True)
        direct_s, _, _, _ = run_wave(direct)
        direct_rate = n_direct * (SIZE_MB << 20) / direct_s
        direct_egress = N_LEECHERS * (SIZE_MB << 20)
        log(f"baseline direct: {n_direct} pulls in {direct_s:.2f}s "
            f"-> {direct_rate / 1e9:.3f} GB/s aggregate (egress for "
            f"{N_LEECHERS} clients = {direct_egress / 1e6:.0f} MB)")

        dbg = os.environ.get("BENCH_DEBUG_DIR")
        seed = Proc(["--role", "seed", os.path.join(workdir, "seed")],
                    stderr_path=dbg and os.path.join(dbg, "seed.err"))
        daemons.append(seed)
        seed_info = seed.read_json()
        sched = Proc(["--role", "scheduler", str(seed_info["rpc_port"]),
                      str(seed_info["download_port"])],
                     stderr_path=dbg and os.path.join(dbg, "sched.err"))
        daemons.append(sched)
        sched_addr = sched.read_json()["addr"]

        # Interleaved half/full cold waves, MEDIAN of each: one wave's
        # wall-clock on this shared host swings 2-3x within minutes, so a
        # single half wave against median-of-3 full waves measures drift,
        # not sublinearity (one run read 8.9x from a lucky half wave).
        # Alternating H,F,H,F,... exposes both sizes to the same drift.
        n_half = max(N_LEECHERS // 2, 1)
        runs = []
        half_runs = []
        n_runs = int(os.environ.get("BENCH_FANOUT_RUNS", "3"))
        for r in range(n_runs):
            half_s_r, _, half_cpu_r, _, half_egress = fanout_wave(
                workdir, f"h{r}x", n_half, sched_addr,
                f"{origin_base}/wave-half-{r}.bin", daemons,
                origin_bytes_fn=origin_bytes)
            half_runs.append({"elapsed_s": half_s_r, "cpu": half_cpu_r})
            log(f"fan-out {n_half} leechers (half run {r}): {half_s_r:.2f}s "
                f"(origin egress {half_egress / 1e6:.0f} MB)")
            fanout_s, seed_fracs, full_cpu, waits, p2p_egress = fanout_wave(
                workdir, f"l{r}x", N_LEECHERS, sched_addr,
                f"{origin_base}/wave-full-{r}.bin", daemons,
                origin_bytes_fn=origin_bytes)
            runs.append({"elapsed_s": fanout_s, "egress": p2p_egress,
                         "seed_fracs": seed_fracs, "cpu": full_cpu,
                         "waits": waits})
            seed_active = "?"
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{seed_info['download_port']}"
                        f"/metrics", timeout=5) as resp:
                    for line in resp.read().decode().splitlines():
                        if line.startswith("df_upload_active_transfers"):
                            seed_active = line.split()[-1]
            except Exception:
                pass
            log(f"fan-out {N_LEECHERS} leechers (run {r}): {fanout_s:.2f}s "
                f"(origin egress {p2p_egress / 1e6:.0f} MB, seed active "
                f"slots after: {seed_active})")
        runs.sort(key=lambda r: r["elapsed_s"])
        med = runs[len(runs) // 2]
        fanout_s, p2p_egress, full_cpu = (med["elapsed_s"], med["egress"],
                                          med["cpu"])
        seed_fracs = med["seed_fracs"]
        # elapsed AND cpu from the median half run (mixing the median
        # elapsed with the last run's cpu pairs different machine moments)
        half_runs.sort(key=lambda h: h["elapsed_s"])
        half_med = half_runs[len(half_runs) // 2]
        half_s, half_cpu = half_med["elapsed_s"], half_med["cpu"]
        egress_saved = 1.0 - p2p_egress / max(direct_egress, 1)
        max_seed_frac = max(seed_fracs) if seed_fracs else 0.0
        med_waits = med.get("waits", {"idle_fracs": [], "seed_wait_fracs": []})
        idle_max = max(med_waits["idle_fracs"], default=0.0)
        idle_med = (statistics.median(med_waits["idle_fracs"])
                    if med_waits["idle_fracs"] else 0.0)
        seed_wait_max = max(med_waits["seed_wait_fracs"], default=0.0)
        log(f"framework fan-out (median of {n_runs}): {N_LEECHERS} leechers "
            f"in {fanout_s:.2f}s (origin egress {p2p_egress / 1e6:.0f} MB, "
            f"saved {egress_saved:.1%}); sublinearity "
            f"{fanout_s / half_s:.2f}x for 2x leechers; max seed-sourced "
            f"fraction {max_seed_frac:.0%}; worker idle med {idle_med:.0%} "
            f"max {idle_max:.0%} (seed-wait max {seed_wait_max:.0%})")

        # CPU-unsaturated sublinearity: same protocol, rates cut far enough
        # that the 1-vCPU host stays below ~80% busy, making the wall-clock
        # scaling falsifiable (at full rates the host saturates and 2x work
        # MUST take ~2x wall regardless of scheduling quality). A dedicated
        # seed+scheduler pair carries the capped NIC model.
        unsat_stats = {}
        if os.environ.get("BENCH_UNSAT", "1") != "0":
            cap_nic = float(os.environ.get("BENCH_UNSAT_NIC_MBPS", "4"))
            cap_env = {"BENCH_NIC_MBPS": str(cap_nic)}
            useed = Proc(["--role", "seed", os.path.join(workdir, "useed")],
                         stderr_path=dbg and os.path.join(dbg, "useed.err"),
                         env=cap_env)
            daemons.append(useed)
            useed_info = useed.read_json()
            usched = Proc(["--role", "scheduler",
                           str(useed_info["rpc_port"]),
                           str(useed_info["download_port"])],
                          stderr_path=dbg and os.path.join(dbg, "usched.err"))
            daemons.append(usched)
            usched_addr = usched.read_json()["addr"]
            uhalf_s, _, uhalf_cpu, _, _ = fanout_wave(
                workdir, "uh", n_half, usched_addr,
                f"{origin_base}/wave-unsat-half.bin", daemons, env=cap_env)
            log(f"unsaturated fan-out {n_half} leechers: {uhalf_s:.2f}s "
                f"(cpu {uhalf_cpu:.0%})")
            ufull_s, _, ufull_cpu, uwaits, _ = fanout_wave(
                workdir, "uf", N_LEECHERS, usched_addr,
                f"{origin_base}/wave-unsat-full.bin", daemons, env=cap_env)
            u_idle_max = max(uwaits["idle_fracs"], default=0.0)
            log(f"unsaturated fan-out {N_LEECHERS} leechers: {ufull_s:.2f}s "
                f"(cpu {ufull_cpu:.0%}) -> sublinearity "
                f"{ufull_s / uhalf_s:.2f}x at NIC {cap_nic:.0f} MB/s, "
                f"worker idle max {u_idle_max:.0%}")
            unsat_stats = {
                "sublinearity_2x_cpu_unsaturated": round(ufull_s / uhalf_s, 3),
                "unsat_nic_mbps": cap_nic,
                "unsat_wave_cpu_util": {"half": round(uhalf_cpu, 3),
                                        "full": round(ufull_cpu, 3)},
                "unsat_runs_s": {"half": round(uhalf_s, 2),
                                 "full": round(ufull_s, 2)},
                "unsat_idle_frac_max": round(u_idle_max, 4),
            }

        # TPU leg: run in a SUBPROCESS with retry-until-deadline. A fresh
        # process per attempt matters: once an in-process jax probe thread
        # hangs on a wedged tunnel it holds jax's init locks forever, so
        # even a recovered tunnel is unreachable from this process. The
        # parent never touches jax at all.
        tpu_stats = _tpu_phase_with_retry(data_path, workdir)
    finally:
        for p in daemons:
            p.kill()
        import shutil
        if os.environ.get("BENCH_DEBUG_DIR"):
            # keep the role daemons' file logs (dflog writes per-concern
            # files under each workdir, not stderr) for stall forensics
            dst = os.path.join(os.environ["BENCH_DEBUG_DIR"], "workdir")
            shutil.rmtree(dst, ignore_errors=True)
            try:
                shutil.copytree(workdir, dst,
                                ignore=shutil.ignore_patterns(
                                    "*.bin", "*.out", "data", "pieces"))
            except Exception:  # noqa: BLE001 - forensics only
                pass
        shutil.rmtree(workdir, ignore_errors=True)

    delivered_gb = (SIZE_MB << 20) * N_LEECHERS / 1e9
    value = delivered_gb / fanout_s
    baseline = direct_rate / 1e9
    print(json.dumps({
        "metric": "p2p_fanout_aggregate_throughput",
        "value": round(value, 3),
        "unit": "GB/s",
        "vs_baseline": round(value / baseline, 3) if baseline else 0.0,
        "egress_saved": round(egress_saved, 3),
        "max_seed_sourced_fraction": round(max_seed_frac, 3),
        "sublinearity_2x": round(fanout_s / half_s, 3),
        "host_cpus": os.cpu_count(),
        "calib_sha256_gbps": _calibrate(),
        "calib_mp_gbps": _calibrate_mp(),
        "wave_cpu_util": {"half": round(half_cpu, 3),
                          "full": round(full_cpu, 3)},
        "fanout_runs_s": [round(r["elapsed_s"], 2) for r in runs],
        "half_runs_s": [round(h["elapsed_s"], 2) for h in half_runs],
        "leecher_idle_frac": {"median": round(idle_med, 4),
                              "max": round(idle_max, 4)},
        "seed_wait_frac_max": round(seed_wait_max, 4),
        **unsat_stats,
        **tpu_stats,
    }))


def _run_role(coro) -> None:
    """asyncio.run with optional cProfile dump (BENCH_PROFILE=dir)."""
    prof_dir = os.environ.get("BENCH_PROFILE")
    if not prof_dir:
        asyncio.run(coro)
        return
    import cProfile
    prof = cProfile.Profile()
    try:
        prof.runcall(asyncio.run, coro)
    finally:
        role = sys.argv[sys.argv.index("--role") + 1]
        prof.dump_stats(os.path.join(prof_dir, f"{role}-{os.getpid()}.prof"))


if __name__ == "__main__":
    if "--role" in sys.argv:
        role = sys.argv[sys.argv.index("--role") + 1]
        args = sys.argv[sys.argv.index("--role") + 2:]
        if role == "origin":
            _run_role(role_origin(args[0], float(args[1])))
        elif role == "seed":
            _run_role(role_seed(args[0]))
        elif role == "scheduler":
            _run_role(role_scheduler(int(args[0]), int(args[1])))
        elif role == "leecher":
            _run_role(role_leecher(args[0], args[1], args[2], args[3]))
        elif role == "direct":
            _run_role(role_direct(args[0], args[1]))
        elif role == "tpu":
            role_tpu(args[0], args[1])
        else:
            raise SystemExit(f"unknown role {role}")
    else:
        main()
