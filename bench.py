"""Benchmark: P2P fan-out aggregate throughput vs naive direct downloads.

Shape of BASELINE config #2 shrunk to one machine, with every component in
its OWN OS process (origin, scheduler, seed daemon, N leecher daemons —
sharing one event loop would measure the GIL, not the framework): an origin
serving a synthetic weights file, one seed daemon, a real scheduler, and N
leechers that must replicate the file with back-source disabled (every byte
rides the mesh). The baseline is N processes each pulling the whole file
straight from the origin — what a fleet without the framework does.

Piece stores live in tmpfs: the TPU-native terminal sink is HBM/host RAM
(tpu/hbm_sink.py), so a ~100 MB/s VM boot disk would measure itself.

Prints ONE JSON line:
  {"metric": ..., "value": GB/s aggregate delivered, "unit": "GB/s",
   "vs_baseline": ours / naive}
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import subprocess
import sys
import tempfile
import time

logging.basicConfig(
    level=logging.DEBUG if os.environ.get("BENCH_DEBUG_DIR") else logging.WARNING,
    stream=sys.stderr)

SIZE_MB = int(os.environ.get("BENCH_SIZE_MB", "128"))
N_LEECHERS = int(os.environ.get("BENCH_LEECHERS", "4"))
ORIGIN_MBPS = float(os.environ.get("BENCH_ORIGIN_MBPS", "64"))
REPO = os.path.dirname(os.path.abspath(__file__))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def ensure_native() -> None:
    so = os.path.join(REPO, "native", "build", "libdfnative.so")
    if not os.path.exists(so):
        subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                       capture_output=True, check=False)


def base_tmp() -> str:
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


# ======================================================================
# worker roles (each runs in its own process: python bench.py --role X)
# ======================================================================

async def role_origin(path: str, mbps: float) -> None:
    """Serve ``path`` with Range support, paced at ``mbps`` MB/s total.

    The cap models the real scarce resource — origin/WAN/GCS egress per
    cluster (BASELINE's "% origin egress saved"). An uncapped loopback
    origin would make any P2P layer look like pure overhead, which is not
    the deployment the reference or this framework exists for. Tracks bytes
    served at /__stats__.
    """
    from aiohttp import web

    from dragonfly2_tpu.common.piece import parse_http_range
    from dragonfly2_tpu.common.rate import TokenBucket

    size = os.path.getsize(path)
    bucket = TokenBucket(mbps * 1e6, burst=4e6) if mbps > 0 else None
    served = {"bytes": 0}

    async def handle(request: web.Request):
        if request.path == "/__stats__":
            return web.json_response(served)
        start, length = 0, size
        status, headers = 200, {"Accept-Ranges": "bytes",
                                "Content-Length": "0"}
        rng = request.headers.get("Range")
        if rng:
            r = parse_http_range(rng, size)
            start, length = r.start, r.length
            status = 206
            headers["Content-Range"] = f"bytes {r.start}-{r.end-1}/{size}"
        headers["Content-Length"] = str(length)
        resp = web.StreamResponse(status=status, headers=headers)
        await resp.prepare(request)
        with open(path, "rb") as f:
            f.seek(start)
            remaining = length
            while remaining > 0:
                chunk = f.read(min(1 << 20, remaining))
                if not chunk:
                    break
                if bucket is not None:
                    await bucket.acquire(len(chunk))
                await resp.write(chunk)
                served["bytes"] += len(chunk)
                remaining -= len(chunk)
        await resp.write_eof()
        return resp

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", handle)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    from dragonfly2_tpu.common.aiohttp_util import resolve_port
    print(json.dumps({"port": resolve_port(runner)}), flush=True)
    await asyncio.Event().wait()


async def role_seed(workdir: str) -> None:
    from dragonfly2_tpu.daemon.config import DaemonConfig, StorageSection
    from dragonfly2_tpu.daemon.daemon import Daemon

    cfg = DaemonConfig(workdir=workdir, host_ip="127.0.0.1", hostname="seed",
                       is_seed=True,
                       storage=StorageSection(gc_interval_s=3600))
    daemon = Daemon(cfg)
    await daemon.start()
    print(json.dumps({"rpc_port": daemon.rpc.port,
                      "download_port": daemon.upload_server.port}), flush=True)
    await asyncio.Event().wait()


async def role_scheduler(seed_rpc: int, seed_dl: int) -> None:
    from dragonfly2_tpu.scheduler import Scheduler, SchedulerConfig
    from dragonfly2_tpu.scheduler.config import SeedPeerAddr

    sched = Scheduler(SchedulerConfig(seed_peers=[SeedPeerAddr(
        ip="127.0.0.1", rpc_port=seed_rpc, download_port=seed_dl)]))
    await sched.start()
    print(json.dumps({"addr": sched.address}), flush=True)
    await asyncio.Event().wait()


async def role_leecher(workdir: str, name: str, sched_addr: str,
                       url: str) -> None:
    from dragonfly2_tpu.daemon.config import (DaemonConfig,
                                              SchedulerConfig as DSched,
                                              StorageSection)
    from dragonfly2_tpu.daemon.daemon import Daemon
    from dragonfly2_tpu.idl.messages import DownloadRequest
    from dragonfly2_tpu.rpc.client import Channel, ServiceClient

    cfg = DaemonConfig(workdir=workdir, host_ip="127.0.0.1", hostname=name,
                       scheduler=DSched(addresses=[sched_addr],
                                        schedule_timeout_s=60.0),
                       storage=StorageSection(gc_interval_s=3600))
    daemon = Daemon(cfg)
    await daemon.start()
    print("READY", flush=True)
    await asyncio.get_running_loop().run_in_executor(None, sys.stdin.readline)

    ch = Channel(f"unix:{daemon.unix_sock}")
    client = ServiceClient(ch, "df.daemon.Daemon")
    out = os.path.join(workdir, "replica.bin")
    t0 = time.monotonic()
    task_id = None
    async for resp in client.unary_stream("Download", DownloadRequest(
            url=url, output=out, disable_back_source=True, timeout_s=600.0)):
        task_id = resp.task_id or task_id
    elapsed = time.monotonic() - t0
    size = os.path.getsize(out)
    sources: dict[str, int] = {}
    engine_state = {}
    conductor = daemon.ptm.conductor(task_id) if task_id else None
    if conductor is not None and conductor.storage is not None:
        for p in conductor.storage.md.pieces.values():
            key = (p.source or "origin")[-10:]
            sources[key] = sources.get(key, 0) + 1
        engine = conductor._p2p_engine
        if engine is not None and os.environ.get("BENCH_DEBUG_DIR"):
            engine_state = {
                pid[-10:]: {"ejected": st.ejected,
                            "nspb": round(st.ns_per_byte, 1),
                            "try": st.attempts, "ann": st.announced}
                for pid, st in engine.dispatcher.parents.items()}
    await ch.close()
    await daemon.stop()
    out_msg = {"elapsed": elapsed, "bytes": size, "sources": sources}
    if engine_state:
        out_msg["parents"] = engine_state
    print(json.dumps(out_msg), flush=True)


async def role_direct(workdir: str, url: str) -> None:
    import aiohttp

    print("READY", flush=True)
    await asyncio.get_running_loop().run_in_executor(None, sys.stdin.readline)
    t0 = time.monotonic()
    got = 0
    out = os.path.join(workdir, "direct.bin")
    async with aiohttp.ClientSession() as session:
        async with session.get(url) as resp:
            with open(out, "wb") as f:
                async for chunk in resp.content.iter_chunked(1 << 20):
                    f.write(chunk)
                    got += len(chunk)
    elapsed = time.monotonic() - t0
    print(json.dumps({"elapsed": elapsed, "bytes": got}), flush=True)


# ======================================================================
# orchestration
# ======================================================================

class Proc:
    def __init__(self, args: list[str], stderr_path: str | None = None):
        stderr = (open(stderr_path, "w") if stderr_path
                  else subprocess.DEVNULL)
        self.p = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "bench.py"), *args],
            stdout=subprocess.PIPE, stderr=stderr,
            stdin=subprocess.PIPE, text=True, cwd=REPO)

    def read_json(self, timeout: float = 120.0):
        line = self._read_line(timeout)
        return json.loads(line)

    def wait_ready(self, timeout: float = 120.0) -> None:
        line = self._read_line(timeout)
        assert line.strip() == "READY", f"unexpected: {line!r}"

    def _read_line(self, timeout: float) -> str:
        import select
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError("worker did not report in time")
            # select before readline: readline() itself blocks and would
            # defeat the deadline when a worker hangs without printing
            ready, _, _ = select.select([self.p.stdout], [], [],
                                        min(remaining, 1.0))
            if ready:
                line = self.p.stdout.readline()
                if line:
                    return line
            if self.p.poll() is not None:
                raise RuntimeError(f"worker died: rc={self.p.returncode}")

    def go(self) -> None:
        self.p.stdin.write("\n")
        self.p.stdin.flush()

    def kill(self) -> None:
        if self.p.poll() is None:
            self.p.kill()
            self.p.wait()


def run_wave(procs: list[Proc]) -> float:
    """READY-barrier, then GO all; returns max elapsed reported."""
    for p in procs:
        p.wait_ready()
    for p in procs:
        p.go()
    results = [p.read_json(timeout=600.0) for p in procs]
    for r in results:
        assert r["bytes"] == SIZE_MB << 20, f"short transfer: {r}"
        if r.get("sources"):
            log(f"  piece sources: {r['sources']} ({r['elapsed']:.2f}s)"
                + (f" parents={r['parents']}" if r.get("parents") else ""))
    return max(r["elapsed"] for r in results)


def main() -> None:
    ensure_native()
    workdir = tempfile.mkdtemp(prefix="dfbench-", dir=base_tmp())
    data_path = os.path.join(workdir, "weights.bin")
    with open(data_path, "wb") as f:
        remaining = SIZE_MB << 20
        while remaining > 0:
            n = min(remaining, 64 << 20)
            f.write(os.urandom(n))
            remaining -= n

    daemons: list[Proc] = []
    try:
        origin = Proc(["--role", "origin", data_path, str(ORIGIN_MBPS)])
        daemons.append(origin)
        origin_base = f"http://127.0.0.1:{origin.read_json()['port']}"
        url = f"{origin_base}/weights.bin"

        import urllib.request

        def origin_bytes() -> int:
            with urllib.request.urlopen(f"{origin_base}/__stats__") as r:
                return json.loads(r.read())["bytes"]

        log(f"bench: {SIZE_MB} MiB x {N_LEECHERS} leechers, origin capped "
            f"at {ORIGIN_MBPS:.0f} MB/s (multi-process)")
        direct = [Proc(["--role", "direct", os.path.join(workdir, f"d{i}"),
                        url]) for i in range(N_LEECHERS)]
        daemons.extend(direct)   # killed on any failure path
        for i in range(N_LEECHERS):
            os.makedirs(os.path.join(workdir, f"d{i}"), exist_ok=True)
        direct_s = run_wave(direct)
        direct_egress = origin_bytes()
        log(f"baseline direct: {direct_s:.2f}s "
            f"(origin egress {direct_egress / 1e6:.0f} MB)")

        seed = Proc(["--role", "seed", os.path.join(workdir, "seed")])
        daemons.append(seed)
        seed_info = seed.read_json()
        sched = Proc(["--role", "scheduler", str(seed_info["rpc_port"]),
                      str(seed_info["download_port"])])
        daemons.append(sched)
        sched_addr = sched.read_json()["addr"]

        pre = origin_bytes()
        leechers = [Proc(["--role", "leecher",
                          os.path.join(workdir, f"l{i}"), f"leech{i}",
                          sched_addr, url],
                         stderr_path=os.environ.get("BENCH_DEBUG_DIR") and
                         os.path.join(os.environ["BENCH_DEBUG_DIR"], f"l{i}.err"))
                    for i in range(N_LEECHERS)]
        daemons.extend(leechers)   # killed on any failure path
        fanout_s = run_wave(leechers)
        p2p_egress = origin_bytes() - pre
        egress_saved = 1.0 - p2p_egress / max(direct_egress, 1)
        log(f"framework fan-out: {fanout_s:.2f}s (origin egress "
            f"{p2p_egress / 1e6:.0f} MB, saved {egress_saved:.0%})")
    finally:
        for p in daemons:
            p.kill()
        import shutil
        shutil.rmtree(workdir, ignore_errors=True)

    delivered_gb = (SIZE_MB << 20) * N_LEECHERS / 1e9
    value = delivered_gb / fanout_s
    baseline = delivered_gb / direct_s
    print(json.dumps({
        "metric": "p2p_fanout_aggregate_throughput",
        "value": round(value, 3),
        "unit": "GB/s",
        "vs_baseline": round(value / baseline, 3) if baseline else 0.0,
    }))


if __name__ == "__main__":
    if "--role" in sys.argv:
        role = sys.argv[sys.argv.index("--role") + 1]
        args = sys.argv[sys.argv.index("--role") + 2:]
        if role == "origin":
            asyncio.run(role_origin(args[0], float(args[1])))
        elif role == "seed":
            asyncio.run(role_seed(args[0]))
        elif role == "scheduler":
            asyncio.run(role_scheduler(int(args[0]), int(args[1])))
        elif role == "leecher":
            asyncio.run(role_leecher(args[0], args[1], args[2], args[3]))
        elif role == "direct":
            asyncio.run(role_direct(args[0], args[1]))
        else:
            raise SystemExit(f"unknown role {role}")
    else:
        main()
